#!/usr/bin/env python
"""tpumx-lint: framework-aware static analysis for the tpu-mx contracts.

PRs 2-5 established hard runtime contracts that, until now, were enforced
only dynamically — by whichever chaos/soak/obs CI schedule happened to
execute the offending branch.  This tool makes them checkable at review
time on EVERY line, including cold error paths no fault schedule reaches:

- **durability** — every state write must go through
  ``checkpoint.atomic_write`` (a raw ``open(path, "w"/"wb")``,
  ``pickle.dump`` or ``np.save`` of a state-shaped path can be torn by a
  preemption and then loaded as garbage; docs/robustness.md).
- **determinism** — library RNG must flow through ``tpu_mx/random.py``'s
  process-global state (a stray ``np.random.*`` draw or fresh
  ``jax.random.PRNGKey`` stream silently escapes the PR-5 resume
  capsules, so a "bit-exact" resume isn't).
- **sync-point** — no implicit device→host syncs (``asnumpy``,
  ``.item()``, ``float()`` on an array) inside the hot paths: fusion
  segment construction, the compiled train step, optimizer updates.
  Hidden syncs are exactly what breaks fusion segments and pipelining
  ("Operator Fusion in XLA", PAPERS.md).
- **concurrency** — ``threading.Thread`` must be explicit about
  lifetime (``daemon=`` or a join), and an attribute guarded by a lock
  at some sites must not be mutated lock-free at others (the class of
  bug behind PR 4's zombie-step fix).
- **telemetry-catalog** — metric-name literals at
  counter/gauge/histogram/span call sites must be in
  ``telemetry.KNOWN_METRICS`` (catches names in branches the runtime
  obs tier never executes; an unknown name is invisible to every
  dashboard).

Zero third-party dependencies: pure ``ast`` + stdlib, and the metric
catalog is extracted *statically* from ``tpu_mx/telemetry.py`` (the tool
never imports the package, so it runs in <1s with no jax in sight).

Suppressions: ``# tpumx-lint: disable=<rule>[,<rule>...] [-- reason]``
on the finding's line, or on a comment-only line directly above it.
Suppress sparingly and always with the ``--`` justification.

Baseline: ``tools/tpumx_lint_baseline.json`` holds fingerprints of
accepted pre-existing findings (``--write-baseline`` regenerates it).
Fingerprints hash (rule, path, enclosing scope, normalized line text) —
stable across unrelated line drift.  The shipped baseline is kept EMPTY:
new findings must be fixed or individually justified inline.

Usage::

    python tools/tpumx_lint.py                  # lint the default tree
    python tools/tpumx_lint.py --format json    # machine-readable (CI)
    python tools/tpumx_lint.py --write-baseline # accept current findings
    python tools/tpumx_lint.py path.py ...      # explicit file set

Exit status: 0 when every finding is suppressed or baselined, 1
otherwise, 2 on usage/internal error.  See docs/static_analysis.md for
the rule catalog and how to add a pass.
"""
from __future__ import annotations

import argparse
import ast
import fnmatch
import hashlib
import json
import os
import re
import sys

LINT_FORMAT = "tpumx-lint-baseline-v1"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the default scan set (ISSUE 6): the library, the tools, the bench driver
DEFAULT_TARGETS = ("tpu_mx", "tools", "bench.py")

_SUPPRESS_RE = re.compile(
    r"#\s*tpumx-lint:\s*disable="
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------
class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "col", "message", "context",
                 "line_text")

    def __init__(self, rule, path, line, col, message, context="",
                 line_text=""):
        self.rule = rule
        self.path = path            # repo-relative, forward slashes
        self.line = line            # 1-based
        self.col = col              # 0-based
        self.message = message
        self.context = context      # enclosing Class.def qualname ("" = module)
        self.line_text = line_text

    def fingerprint(self):
        """Stable identity for baselining: hashes the rule, file, enclosing
        scope and the normalized source line — NOT the line number, so
        unrelated edits above a baselined finding don't resurrect it."""
        norm = " ".join(self.line_text.split())
        raw = "|".join((self.rule, self.path, self.context, norm))
        return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "context": self.context, "fingerprint": self.fingerprint()}

    def render(self):
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"[{self.rule}] {self.message}")


# ---------------------------------------------------------------------------
# per-file context shared by every pass
# ---------------------------------------------------------------------------
class FileCtx:
    """Parsed file + the lookups the passes share: source lines, a
    node→enclosing-scope map, and the module's import aliases."""

    def __init__(self, path, source):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.scope = {}        # id(node) -> "Class.method" qualname
        self.func_of = {}      # id(node) -> nearest FunctionDef node (or None)
        self.class_of = {}     # id(node) -> nearest ClassDef node (or None)
        self._index_scopes()
        # import aliases: local name -> dotted module it refers to
        self.mod_alias = {}    # e.g. {"np": "numpy", "_telemetry": "...telemetry"}
        self.from_imports = {} # local name -> (module, original name)
        self._index_imports()

    def _index_scopes(self):
        def walk(node, qual, func, klass):
            for child in ast.iter_child_nodes(node):
                q, f, k = qual, func, klass
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    f = child
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    k = child
                self.scope[id(child)] = qual
                self.func_of[id(child)] = func
                self.class_of[id(child)] = klass
                walk(child, q, f, k)
        walk(self.tree, "", None, None)

    def _index_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_alias[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (mod, a.name)

    def qualname(self, node):
        return self.scope.get(id(node), "")

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, node, message):
        return Finding(rule, self.path, node.lineno, node.col_offset,
                       message, context=self.qualname(node),
                       line_text=self.line_text(node.lineno))


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------
def dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call):
    return dotted(call.func)


def const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def strings_in(node):
    """Every string constant anywhere inside `node` (e.g. both arms of a
    conditional mode expression)."""
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def expr_text(node):
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — unparse handles all real exprs
        return ""


def _numpy_names(ctx):
    """Local aliases that refer to the host numpy module."""
    return {alias for alias, mod in ctx.mod_alias.items()
            if mod in ("numpy", "numpy.random")} | {"np", "onp", "_np"}


# ---------------------------------------------------------------------------
# rule passes
# ---------------------------------------------------------------------------
class Pass:
    """One rule pass.  Subclasses set `name` and implement `run(ctx)`
    yielding Findings.  Adding a pass = subclass + append to PASSES
    (docs/static_analysis.md walks through an example)."""

    name = None

    def run(self, ctx):  # pragma: no cover — interface
        raise NotImplementedError


class DurabilityPass(Pass):
    """Raw state writes that bypass checkpoint.atomic_write.

    Flags, in library code (``tpu_mx/``): any ``open(path, "w"/"wb")``,
    any ``pickle.dump(obj, file)``, and ``np.save/np.savez`` to anything
    not provably an in-memory buffer.  In ``tools/``/``bench.py`` only
    *state-shaped* paths are flagged (ones whose expression mentions
    checkpoints/params/states/manifests) — report files there are not
    recovery state.  ``atomic_write``'s own internal ``open`` is the one
    structural allowlist: it IS the durability layer.
    """

    name = "durability"

    STATE_HINTS = ("params", "states", "checkpoint", "ckpt", "manifest",
                   "capsule", "lastgood")

    def _is_library(self, ctx):
        return ctx.path.startswith("tpu_mx/")

    def _state_shaped(self, arg):
        text = expr_text(arg).lower()
        return any(h in text for h in self.STATE_HINTS)

    def _in_scope(self, ctx, path_arg):
        return self._is_library(ctx) or self._state_shaped(path_arg)

    def _bytesio_fed(self, ctx, call, arg):
        """True when `arg` is (or is assigned from) an io.BytesIO — an
        in-memory sink, no durability contract applies."""
        if any("BytesIO" in (dotted(n) or "")
               for n in ast.walk(arg) if isinstance(n, (ast.Name, ast.Attribute))):
            return True
        if isinstance(arg, ast.Name):
            func = ctx.func_of.get(id(call))
            search = func if func is not None else ctx.tree
            for node in ast.walk(search):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == arg.id
                        for t in node.targets):
                    if "BytesIO" in expr_text(node.value):
                        return True
        return False

    def run(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            # --- open(path, "w"/"wb") --------------------------------
            if fn == "open" and node.args:
                func = ctx.func_of.get(id(node))
                if func is not None and func.name == "atomic_write":
                    continue  # the durability layer's own tmp-file open
                mode = None
                if len(node.args) >= 2:
                    mode = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                if mode is None:
                    continue  # default "r"
                modes = strings_in(mode)
                if not any(m.startswith("w") for m in modes):
                    continue
                if not self._in_scope(ctx, node.args[0]):
                    continue
                yield ctx.finding(
                    self.name, node,
                    f"raw open({expr_text(node.args[0])}, "
                    f"{'/'.join(sorted(set(modes)))}) write bypasses "
                    "checkpoint.atomic_write — a crash mid-write leaves a "
                    "truncated destination (docs/robustness.md)")
            # --- pickle.dump(obj, file) ------------------------------
            elif fn is not None and fn.endswith("pickle.dump"):
                if not self._is_library(ctx) and not (
                        len(node.args) >= 2
                        and self._state_shaped(node.args[1])):
                    continue
                yield ctx.finding(
                    self.name, node,
                    "pickle.dump to a raw file handle bypasses "
                    "checkpoint.atomic_write — use pickle.dumps + "
                    "atomic_write so the commit is all-or-nothing")
            # --- np.save / np.savez(path, ...) -----------------------
            elif fn is not None and node.args and any(
                    fn == f"{alias}.{save}"
                    for alias in _numpy_names(ctx)
                    for save in ("save", "savez", "savez_compressed")):
                sink = node.args[0]
                if self._bytesio_fed(ctx, node, sink):
                    continue  # in-memory serialize-then-atomic_write idiom
                if not self._in_scope(ctx, sink):
                    continue
                yield ctx.finding(
                    self.name, node,
                    f"{fn}({expr_text(sink)}, ...) writes state in place — "
                    "serialize to BytesIO and commit via "
                    "checkpoint.atomic_write")


class DeterminismPass(Pass):
    """Library RNG outside the tpu_mx.random process-global state.

    Flags, in ``tpu_mx/`` (the framework's own ``random.py`` excepted):
    draws/seeds on numpy's global stream (``np.random.rand`` etc. —
    route through ``tpu_mx.random.host_rng()`` so the dependence on the
    capsule-covered stream is explicit), fresh ``jax.random.PRNGKey``
    streams (escape the capsule entirely), entropy-seeded
    ``RandomState()``/``default_rng()`` (irreproducible by
    construction), and time-seeded RNG anywhere.  A *seeded* private
    ``RandomState(seed)`` is NOT flagged — that is the blessed pattern
    for iterators that snapshot their own stream via ``state_dict()``.
    """

    name = "determinism"

    GLOBAL_DRAWS = frozenset({
        "seed", "rand", "randn", "randint", "random", "random_sample",
        "ranf", "sample", "uniform", "normal", "standard_normal",
        "shuffle", "permutation", "choice", "beta", "gamma", "binomial",
        "multinomial", "poisson", "exponential", "laplace", "bytes",
    })
    SEEDED_CTORS = ("RandomState", "default_rng")

    def _library(self, ctx):
        return (ctx.path.startswith("tpu_mx/")
                and ctx.path != "tpu_mx/random.py")

    @staticmethod
    def _has_seed_arg(call):
        """True when the RNG constructor receives a non-None seed, either
        positionally or as a keyword (RandomState(seed=7))."""
        if call.args and not (isinstance(call.args[0], ast.Constant)
                              and call.args[0].value is None):
            return True
        return any(not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
                   for kw in call.keywords if kw.arg is not None)

    def _time_seeded(self, node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = call_name(sub) or ""
                if d in ("time.time", "time.time_ns", "time.monotonic",
                         "time.perf_counter"):
                    return True
        return False

    def run(self, ctx):
        lib = self._library(ctx)
        np_names = _numpy_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            if fn is None:
                continue
            parts = fn.split(".")
            # time-seeded RNG is wrong EVERYWHERE (tools included): the
            # run is irreproducible and the seed is unrecorded.  Both
            # positional and keyword (seed=time.time()) spellings count.
            seedish = list(node.args) + [kw.value for kw in node.keywords]
            if (parts[-1] in ("seed", "PRNGKey", "key", "Random")
                    + self.SEEDED_CTORS
                    and any(self._time_seeded(a) for a in seedish)):
                yield ctx.finding(
                    self.name, node,
                    f"{fn} seeded from wall-clock time — the stream is "
                    "unrecorded and can never be replayed by a resume "
                    "capsule; derive the seed from tpu_mx.random or config")
                continue
            if not lib:
                continue
            # np.random.<draw> on the GLOBAL numpy stream
            if (len(parts) >= 3 and parts[-2] == "random"
                    and parts[-3] in np_names
                    and parts[-1] in self.GLOBAL_DRAWS):
                yield ctx.finding(
                    self.name, node,
                    f"direct {fn} draws from numpy's global stream — "
                    "route through tpu_mx.random.host_rng() (the "
                    "capsule-covered stream) or a private seeded "
                    "RandomState with state_dict coverage")
            # fresh jax PRNGKey/typed-key stream outside tpu_mx/random.py
            # (jax.random.key is the current recommended constructor —
            # same capsule-escape as the legacy PRNGKey)
            elif parts[-1] == "PRNGKey" or (
                    len(parts) >= 2 and parts[-2] == "random"
                    and parts[-1] == "key"):
                yield ctx.finding(
                    self.name, node,
                    f"fresh {parts[-1]} stream escapes the "
                    "process-global tpu_mx.random state — resume capsules "
                    "cannot replay it; use tpu_mx.random.take_key()")
            # entropy-seeded private streams (a seed passed positionally
            # OR as seed=/... keyword makes the stream reproducible)
            elif parts[-1] in self.SEEDED_CTORS and (
                    len(parts) < 3 or parts[-2] == "random") and (
                    not self._has_seed_arg(node)):
                yield ctx.finding(
                    self.name, node,
                    f"{fn} with no seed draws OS entropy — the stream is "
                    "irreproducible; seed it from config or "
                    "tpu_mx.random")


class SyncPointPass(Pass):
    """Implicit device→host syncs inside the hot paths.

    Hot scopes: ``tpu_mx/fusion.py`` and ``tpu_mx/parallel/train_step.py``
    (whole files — segment construction and the step dispatch path), and
    optimizer ``update*``/``create_state*`` bodies.  Flags ``.asnumpy()``
    / ``.item()`` / ``.tolist()`` / ``jax.device_get`` /
    host-``np.asarray(...)`` calls, and ``float()/bool()/int()`` applied
    to a call or subscript result (an array reduction like
    ``float(loss.mean())`` blocks dispatch; ``float(self.lr)`` on plain
    attributes stays silent).  Explicit syncs (``wait_to_read``,
    ``block_until_ready``) are allowed — the contract is that a sync must
    be *visible*, not that it never happens.
    """

    name = "sync-point"

    HOT_FILES = ("tpu_mx/fusion.py", "tpu_mx/parallel/train_step.py")
    HOT_FUNC_FILES = ("tpu_mx/optimizer/", )
    HOT_FUNC_PREFIXES = ("update", "_update", "create_state", "step")
    IMPLICIT = ("asnumpy", "item", "tolist", "asscalar")
    # method-style array reductions: float(loss.mean()) blocks on device.
    # Module-level host calls (np.prod(shape)) and dict methods (.get)
    # are host work — the nearest legitimate look-alikes, left silent.
    REDUCTIONS = frozenset({"mean", "sum", "max", "min", "norm", "prod",
                            "all", "any", "dot"})

    def _hot(self, ctx, node):
        if ctx.path in self.HOT_FILES:
            return True
        if any(ctx.path.startswith(p) for p in self.HOT_FUNC_FILES):
            func = ctx.func_of.get(id(node))
            while func is not None:
                if any(func.name.startswith(p)
                       for p in self.HOT_FUNC_PREFIXES):
                    return True
                func = ctx.func_of.get(id(func))
        return False

    def run(self, ctx):
        hot_possible = (ctx.path in self.HOT_FILES
                        or any(ctx.path.startswith(p)
                               for p in self.HOT_FUNC_FILES))
        if not hot_possible:
            return
        np_names = _numpy_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not self._hot(ctx, node):
                continue
            fn = call_name(node)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.IMPLICIT
                    and not node.args and not node.keywords):
                yield ctx.finding(
                    self.name, node,
                    f".{node.func.attr}() forces a device→host sync on the "
                    "hot path — it stalls dispatch and flushes/splits any "
                    "fusion segment; hoist it out or make the sync "
                    "explicit at the loop level")
            elif fn == "jax.device_get" or (
                    fn is not None and "." in fn
                    and fn.split(".")[0] in np_names
                    and fn.split(".")[-1] in ("asarray", "array")
                    and ctx.path in self.HOT_FILES):
                yield ctx.finding(
                    self.name, node,
                    f"{fn}(...) copies device memory to host on the hot "
                    "path — an implicit sync; keep data on device "
                    "(jnp.asarray) or sync explicitly outside the step")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "bool", "int")
                  and node.args
                  and isinstance(node.args[0], ast.Call)
                  and isinstance(node.args[0].func, ast.Attribute)
                  and node.args[0].func.attr in self.REDUCTIONS
                  and not (isinstance(node.args[0].func.value, ast.Name)
                           and node.args[0].func.value.id in np_names)):
                yield ctx.finding(
                    self.name, node,
                    f"{node.func.id}({expr_text(node.args[0])}) on the hot "
                    "path blocks until the device value materializes — an "
                    "implicit sync point; read it back outside the step "
                    "or keep the value on device")


class ConcurrencyPass(Pass):
    """Thread-lifetime and lock-discipline contracts.

    (a) ``threading.Thread(...)`` must pass an explicit ``daemon=``; a
    non-daemon thread must additionally be ``.join()``-ed somewhere in
    the file (otherwise interpreter shutdown can hang on it — the
    watchdog/generation discipline from PR 4).
    (b) Per class: a ``self.X`` attribute that is assigned under a
    ``with self.<lock>:`` block at ANY site must not be assigned
    lock-free at another site (``__init__`` excepted — before the object
    escapes, no thread can see it).  Mixed discipline is exactly the
    zombie-step class of race.
    (c) Per MODULE: a module-level global that is assigned/mutated under
    a ``with <module_lock>:`` block at ANY site must not be mutated
    lock-free in another function (module top level — import time,
    single-threaded — excepted).  The ``checkpoint._intended`` /
    ``_intended_lock`` shape, and the serving KV-cache free list's:
    the PR-6 linter only saw class-scoped pairs (ROADMAP limitation,
    closed in ISSUE 8).  Covered mutations: ``global X; X = ...``,
    ``X[...] = ...`` and ``X.attr = ...`` where X is a module-level
    name (plus their aug/annotated forms); method CALLS
    (``X.append(...)``) are not assignments and stay out of scope —
    lexical analysis, same bar as the class rule.
    """

    name = "concurrency"

    def run(self, ctx):
        yield from self._threads(ctx)
        yield from self._lock_discipline(ctx)
        yield from self._module_lock_discipline(ctx)

    @staticmethod
    def _thread_joins(ctx):
        """Receiver texts of `<expr>.join(...)` calls that can plausibly
        be thread joins — string `", ".join` and `os.path.join` (any
        path-module join) are excluded, so they cannot satisfy the
        non-daemon rule vacuously."""
        joins = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                recv = node.func.value
                if isinstance(recv, ast.Constant):
                    continue  # ", ".join(...)
                text = expr_text(recv)
                if text.endswith("path") or ".path" in text:
                    continue  # os.path.join / posixpath.join
                joins.add(text)
        return joins

    def _threads(self, ctx):
        joins = self._thread_joins(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            if fn is None:
                continue
            if fn.endswith("threading.Thread"):
                pass
            elif isinstance(node.func, ast.Name):
                # `from threading import Thread [as T]` — resolve the
                # alias; a class merely NAMED Thread from elsewhere is
                # not ours
                mod, orig = ctx.from_imports.get(node.func.id, ("", ""))
                if orig != "Thread" or mod.split(".")[-1] != "threading":
                    continue
            else:
                continue
            daemon = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = kw.value
            if daemon is None:
                yield ctx.finding(
                    self.name, node,
                    "threading.Thread without an explicit daemon= — "
                    "decide the lifetime: daemon=True (watchdog-style, "
                    "may die mid-write) or daemon=False with a join")
            elif (isinstance(daemon, ast.Constant)
                  and daemon.value is False and not joins):
                yield ctx.finding(
                    self.name, node,
                    "non-daemon Thread with no .join() anywhere in this "
                    "file — interpreter shutdown will hang on it")

    def _is_lock_with(self, item):
        d = dotted(item.context_expr) or ""
        return d.startswith("self.") and "lock" in d.lower()

    @staticmethod
    def _flat_targets(node):
        # Assign has .targets; AugAssign and AnnAssign have one .target
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        flat = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                flat.extend(t.elts)
            else:
                flat.append(t)
        return flat

    def _lock_discipline(self, ctx):
        for klass in ast.walk(ctx.tree):
            if not isinstance(klass, ast.ClassDef):
                continue
            guarded = {}    # attr -> first guarded-assign node
            unguarded = {}  # attr -> [unguarded-assign nodes]

            def visit(node, locked, in_init):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.ClassDef):
                        continue  # nested class: analyzed on its own
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        # a direct method's nearest enclosing function is
                        # the class's own (None at module level); anything
                        # deeper is a closure inside a method
                        direct = (ctx.class_of.get(id(child)) is klass
                                  and ctx.func_of.get(id(child))
                                  is ctx.func_of.get(id(klass)))
                        # a function DEFINED under a lock does not RUN
                        # under it; a closure inside __init__ still runs
                        # during construction (keeps in_init)
                        visit(child, False,
                              child.name == "__init__" if direct
                              else in_init)
                        continue
                    child_locked = locked
                    if isinstance(child, ast.With) and any(
                            self._is_lock_with(i) for i in child.items):
                        child_locked = True
                    if isinstance(child, (ast.Assign, ast.AugAssign,
                                          ast.AnnAssign)) and not (
                            isinstance(child, ast.AnnAssign)
                            and child.value is None):  # bare annotation
                        for t in self._flat_targets(child):
                            d = dotted(t) or ""
                            if not d.startswith("self.") or d.count(".") != 1:
                                continue
                            attr = d.split(".", 1)[1]
                            if locked:
                                guarded.setdefault(attr, child)
                            elif not in_init:
                                unguarded.setdefault(attr, []).append(child)
                    visit(child, child_locked, in_init)

            visit(klass, False, False)
            for attr, sites in unguarded.items():
                if attr not in guarded:
                    continue
                g = guarded[attr]
                for site in sites:
                    yield ctx.finding(
                        self.name, site,
                        f"self.{attr} is assigned under a lock at "
                        f"{ctx.path}:{g.lineno} but lock-free here — "
                        "mixed discipline races exactly like the PR-4 "
                        "zombie-step bug; take the lock (or document why "
                        "this site is single-threaded)")


    # -- (c) module-level lock/global discipline -----------------------------
    def _is_module_lock_with(self, item):
        d = dotted(item.context_expr) or ""
        return d and not d.startswith("self.") and "lock" in d.lower()

    @staticmethod
    def _locals_of(fn):
        """(local names, declared globals) of a function: parameters plus
        bare-Name assignment/loop targets anywhere inside (nested scopes
        included — over-approximating locals under-approximates findings,
        the safe direction for a lexical rule)."""
        if fn is None:
            return frozenset(), frozenset()
        args = fn.args
        params = {a.arg for a in (args.args + args.kwonlyargs
                                  + getattr(args, "posonlyargs", []))}
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        declared_global, assigned = set(), set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Global):
                declared_global.update(n.names)
            elif isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in ConcurrencyPass._flat_targets(n):
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        assigned.add(t.id)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if item.optional_vars is not None:
                        for t in ast.walk(item.optional_vars):
                            if isinstance(t, ast.Name):
                                assigned.add(t.id)
        return params | (assigned - declared_global), declared_global

    def _module_lock_discipline(self, ctx):
        mod_globals = set()
        for node in ctx.tree.body:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in self._flat_targets(node):
                    if isinstance(t, ast.Name):
                        mod_globals.add(t.id)
        # names declared `global` anywhere also count (first assignment
        # may happen inside a function)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Global):
                mod_globals.update(node.names)
        if not mod_globals:
            return
        guarded = {}    # global name -> first guarded-mutation node
        unguarded = {}  # global name -> [unguarded-mutation nodes]
        locals_cache = {}

        def target_global(t, fn):
            """The module-global name this target mutates, or None."""
            if id(fn) not in locals_cache:
                locals_cache[id(fn)] = self._locals_of(fn)
            local_names, declared_global = locals_cache[id(fn)]
            if isinstance(t, ast.Name):
                # a bare-name rebind targets the module global only
                # under an explicit `global` declaration
                return t.id if (t.id in declared_global
                                and t.id in mod_globals) else None
            node = t
            while isinstance(node, (ast.Subscript, ast.Attribute)):
                node = node.value
            if isinstance(node, ast.Name) and node.id in mod_globals \
                    and node.id not in local_names:
                return node.id
            return None

        def visit(node, locked, exempt, fn):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # function bodies run post-import (not exempt); a
                    # function DEFINED under a lock does not RUN under it
                    visit(child, False, False, child)
                    continue
                if isinstance(child, ast.ClassDef):
                    # a class BODY executes at import time (exempt like
                    # module level); its methods hit the branch above
                    visit(child, False, exempt, fn)
                    continue
                child_locked = locked
                if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                        self._is_module_lock_with(i) for i in child.items):
                    child_locked = True
                if isinstance(child, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)) and not (
                        isinstance(child, ast.AnnAssign)
                        and child.value is None):  # bare annotation
                    for t in self._flat_targets(child):
                        name = target_global(t, fn)
                        if name is None:
                            continue
                        if locked:
                            guarded.setdefault(name, child)
                        elif not exempt:
                            unguarded.setdefault(name, []).append(child)
                visit(child, child_locked, exempt, fn)

        visit(ctx.tree, False, True, None)
        for name, sites in unguarded.items():
            if name not in guarded:
                continue
            g = guarded[name]
            for site in sites:
                yield ctx.finding(
                    self.name, site,
                    f"module global {name!r} is mutated under a lock at "
                    f"{ctx.path}:{g.lineno} but lock-free here — mixed "
                    "discipline on module-level shared state (the "
                    "checkpoint._intended shape); take the lock (or "
                    "document why this site is single-threaded)")


class TelemetryCatalogPass(Pass):
    """Names at emission sites must be in their static catalog.

    Two catalogs, one discipline (stable names are an API,
    docs/observability.md): metric names at
    ``<telemetry>.counter/gauge/histogram/span(...)`` call sites are
    checked against ``telemetry.KNOWN_METRICS``, and flight-recorder
    event names at ``<tracing>.emit(...)`` call sites against
    ``tracing.KNOWN_EVENTS`` (any alias whose import resolves to the
    respective module, or functions imported from it).  A literal name
    outside the catalog — even in a branch the obs CI tier never
    executes — fails; a non-literal name is flagged as unverifiable.
    Each catalog's home module is exempt (it manipulates records
    generically).
    """

    name = "telemetry-catalog"

    EMITTERS = frozenset({"counter", "gauge", "histogram", "span"})
    TRACE_EMITTERS = frozenset({"emit"})

    def __init__(self, known_metrics, known_events=None):
        self.known = known_metrics
        self.known_events = known_events

    @staticmethod
    def _aliases(ctx, module, emitters):
        mods = {alias for alias, mod in ctx.mod_alias.items()
                if mod.split(".")[-1] == module}
        # `from tpu_mx import telemetry [as _telemetry]` — the module is
        # the imported NAME here, not the from-module path
        mods |= {alias for alias, (_, name) in ctx.from_imports.items()
                 if name == module}
        funcs = {alias for alias, (mod, name) in ctx.from_imports.items()
                 if name in emitters and mod.split(".")[-1] == module}
        return mods, funcs

    def _check(self, ctx, module, emitters, known, catalog_name):
        if ctx.path == f"tpu_mx/{module}.py" or known is None:
            return
        mods, funcs = self._aliases(ctx, module, emitters)
        if not mods and not funcs:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            is_emit = False
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in emitters
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in mods):
                is_emit = True
            elif isinstance(node.func, ast.Name) and node.func.id in funcs:
                is_emit = True
            if not is_emit or not node.args:
                continue
            name = const_str(node.args[0])
            if name is None:
                yield ctx.finding(
                    self.name, node,
                    f"name {expr_text(node.args[0])!r} is not a string "
                    f"literal — {catalog_name} cannot verify it "
                    "statically; emit a literal name (labels/payload "
                    "fields carry the dynamic part)")
            elif name not in known:
                yield ctx.finding(
                    self.name, node,
                    f'name "{name}" is not in {catalog_name} — '
                    "dashboards and the black-box schema will never see "
                    "it; add it to the catalog (and "
                    "docs/observability.md) or fix the typo")

    def run(self, ctx):
        yield from self._check(ctx, "telemetry", self.EMITTERS,
                               self.known, "telemetry.KNOWN_METRICS")
        yield from self._check(ctx, "tracing", self.TRACE_EMITTERS,
                               self.known_events, "tracing.KNOWN_EVENTS")


# ---------------------------------------------------------------------------
# catalog extraction (static — never imports tpu_mx)
# ---------------------------------------------------------------------------
def _load_catalog(repo, module, var):
    """Extract a literal catalog assignment from tpu_mx/<module>.py by
    parsing it — no package import, so the linter needs no jax and runs
    anywhere.  Dict literals yield their key set."""
    path = os.path.join(repo, "tpu_mx", f"{module}.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == var
                for t in node.targets):
            value = node.value
            if (isinstance(value, ast.Call)
                    and (dotted(value.func) == "frozenset")
                    and value.args):
                value = value.args[0]
            try:
                return frozenset(ast.literal_eval(value))
            except ValueError:
                return None
    return None


def load_known_metrics(repo=REPO):
    """KNOWN_METRICS from tpu_mx/telemetry.py (statically parsed)."""
    return _load_catalog(repo, "telemetry", "KNOWN_METRICS")


def load_known_events(repo=REPO):
    """KNOWN_EVENTS names from tpu_mx/tracing.py (statically parsed;
    the catalog is a dict of name -> typed payload fields — the event
    NAMES are what emit() call sites are checked against)."""
    return _load_catalog(repo, "tracing", "KNOWN_EVENTS")


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------
def suppressed_rules(ctx, lineno):
    """Rules disabled for `lineno` via an inline comment on the line, or
    anywhere in the contiguous comment-only block directly above it (so a
    multi-line justification can lead with the directive)."""
    rules = set()

    def collect(text):
        m = _SUPPRESS_RE.search(text)
        if m:
            rules.update(r.strip() for r in m.group(1).split(",")
                         if r.strip())

    collect(ctx.line_text(lineno))
    ln = lineno - 1
    while ln >= 1 and ctx.line_text(ln).lstrip().startswith("#"):
        collect(ctx.line_text(ln))
        ln -= 1
    return rules


def read_baseline(path):
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return set()
    except ValueError as e:
        raise SystemExit(f"tpumx-lint: baseline {path} unreadable: {e}")
    if data.get("format") != LINT_FORMAT:
        raise SystemExit(f"tpumx-lint: baseline {path}: unknown format "
                         f"{data.get('format')!r}")
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path, findings):
    entries = [{"fingerprint": f.fingerprint(), "rule": f.rule,
                "path": f.path, "context": f.context,
                "line": f.line, "message": f.message}
               for f in findings]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    payload = {"format": LINT_FORMAT,
               "note": "Accepted pre-existing findings; regenerate with "
                       "tools/tpumx_lint.py --write-baseline.  Keep this "
                       "EMPTY: prefer a fix, or an inline justified "
                       "'# tpumx-lint: disable=<rule> -- why'.",
               "findings": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def build_passes(known_metrics, known_events=None):
    return [DurabilityPass(), DeterminismPass(), SyncPointPass(),
            ConcurrencyPass(),
            TelemetryCatalogPass(known_metrics, known_events)]


def lint_source(source, relpath, known_metrics=None, rules=None,
                known_events=None):
    """Lint one in-memory file; returns (findings, suppressed) lists.
    `relpath` decides scoping (library vs tools vs hot path), so tests
    can exercise any scope with fixture paths."""
    ctx = FileCtx(relpath, source)
    findings, suppressed = [], []
    for p in build_passes(known_metrics, known_events):
        if rules and p.name not in rules:
            continue
        for f in p.run(ctx):
            sup = suppressed_rules(ctx, f.line)
            if p.name in sup or "all" in sup:
                suppressed.append(f)
            else:
                findings.append(f)
    return findings, suppressed


def iter_files(targets, repo=REPO, missing=None):
    for t in targets:
        full = t if os.path.isabs(t) else os.path.join(repo, t)
        if not os.path.isfile(full) and not os.path.isdir(full) \
                and os.path.exists(t):
            full = os.path.abspath(t)  # relative to CWD, not the repo
        if os.path.isfile(full):
            yield full
        elif not os.path.isdir(full):
            # a typo'd target must NOT read as a clean lint
            if missing is not None:
                missing.append(t)
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        yield os.path.join(dirpath, fname)


def lint_paths(targets, repo=REPO, known_metrics=None, rules=None,
               known_events=None):
    all_findings, all_suppressed, errors = [], [], []
    missing = []
    for path in iter_files(targets, repo, missing=missing):
        rel = os.path.relpath(os.path.abspath(path), repo)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            found, sup = lint_source(source, rel, known_metrics, rules,
                                     known_events=known_events)
        except SyntaxError as e:
            errors.append(f"{rel}: syntax error: {e}")
            continue
        all_findings.extend(found)
        all_suppressed.extend(sup)
    errors.extend(f"target not found: {t}" for t in missing)
    return all_findings, all_suppressed, errors


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpumx_lint",
        description="framework-aware static analysis for tpu-mx contracts")
    ap.add_argument("targets", nargs="*", default=list(DEFAULT_TARGETS),
                    help="files/dirs to lint (default: tpu_mx tools "
                         "bench.py)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "tools",
                                         "tpumx_lint_baseline.json"))
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    opts = ap.parse_args(argv)

    rules = None
    if opts.rules:
        rules = {r.strip() for r in opts.rules.split(",") if r.strip()}
        valid = {p.name for p in build_passes(frozenset())}
        unknown = rules - valid
        if unknown:
            ap.error(f"unknown rules: {sorted(unknown)} "
                     f"(valid: {sorted(valid)})")

    known = load_known_metrics()
    known_events = load_known_events()
    if (known is None or known_events is None) \
            and (rules is None or "telemetry-catalog" in rules):
        # failing OPEN here would silently disable the whole catalog
        # pass (e.g. after a refactor that makes KNOWN_METRICS /
        # KNOWN_EVENTS a computed expression the static extractor can't
        # evaluate)
        missing = "KNOWN_METRICS from tpu_mx/telemetry.py" \
            if known is None else "KNOWN_EVENTS from tpu_mx/tracing.py"
        print(f"tpumx-lint: could not extract {missing} — the "
              "telemetry-catalog pass cannot run; keep the catalog a "
              "literal frozenset({...}) / dict and update "
              "load_known_metrics()/load_known_events()", file=sys.stderr)
        return 2

    findings, suppressed, errors = lint_paths(
        opts.targets, known_metrics=known, rules=rules,
        known_events=known_events)

    if opts.write_baseline:
        write_baseline(opts.baseline, findings)
        print(f"tpumx-lint: baselined {len(findings)} finding(s) -> "
              f"{opts.baseline}")
        return 0

    baseline = set() if opts.no_baseline else read_baseline(opts.baseline)
    fresh = [f for f in findings if f.fingerprint() not in baseline]
    baselined = len(findings) - len(fresh)

    if opts.format == "json":
        print(json.dumps({
            "findings": [f.as_dict() for f in fresh],
            "baselined": baselined,
            "suppressed": len(suppressed),
            "errors": errors,
            "known_metrics_loaded": known is not None,
            "known_events_loaded": known_events is not None,
        }, indent=1, sort_keys=True))
    else:
        for f in fresh:
            print(f.render())
        for e in errors:
            print(f"error: {e}")
        print(f"tpumx-lint: {len(fresh)} finding(s), "
              f"{baselined} baselined, {len(suppressed)} suppressed"
              + ("" if known is not None else
                 " [WARNING: KNOWN_METRICS catalog not loaded]"))
    if errors:
        return 2
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
