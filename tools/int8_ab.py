"""INT8 vs float inference A/B at fixed batch on chip (VERDICT r4 ask#7).

The r4 quantized-inference number (146 img/s after the jit fix) was only
ever compared to its own eager baseline (16 img/s), never to FLOAT
inference of the same net at the same batch — and divergence #21 already
concedes bf16 is the TPU fast path (the MXU has no native int8 advantage
the way GPU dp4a/IMMA tensor cores do).  This measures, for the model-zoo
ResNet-50 at a fixed batch:

  - f32 inference (hybridized, one XLA program),
  - bf16 inference (cast net — the production serving path),
  - INT8 inference (contrib.quantization.quantize_net, its own jit),

plus the parameter-memory footprint of each arm — if int8 loses on
throughput, its honest value is weight memory/serving footprint, and the
artifact says so with numbers.  Artifact: INT8_AB_<round>.json
(merge-on-write, TPU-only).

    python tools/int8_ab.py [--batch 128] [--iters 20]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# exported for tpu_watch's done-predicate (drift-proofing)
ARMS = ("f32", "bf16", "int8")


def log(msg):
    print(f"[int8_ab {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def _timed(fn, fetch, warmup, iters):
    out = fn()
    fetch(out)
    for _ in range(warmup):
        fetch(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    fetch(out)
    return (time.perf_counter() - t0) / iters


def _param_bytes(params):
    import numpy as np
    total = 0
    for p in params.values():
        d = getattr(p, "_data", None)
        if d is None and callable(getattr(p, "data", None)):
            d = p.data()
        if d is not None:
            total += d.size * np.dtype(str(d.dtype)).itemsize
    return total


def main():
    from artifact_protocol import (artifact, load_prior,
                                   merge_prior_sections, refuses_clobber,
                                   write_atomic)
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=artifact("INT8_AB"))
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="tiny-shape CPU pass through the full code path")
    args = ap.parse_args()

    import jax
    if args.cpu_smoke:
        jax.config.update("jax_platforms", "cpu")
        args.batch, args.iters, args.warmup = 2, 1, 0
        if args.out == artifact("INT8_AB"):
            args.out = "/tmp/int8_ab_smoke.json"
    from tpu_mx.runtime import enable_shared_compilation_cache, fetch_sync
    enable_shared_compilation_cache()
    platform = jax.devices()[0].platform
    prior = load_prior(args.out)
    if refuses_clobber(prior, platform) or \
            (platform != "tpu" and not args.cpu_smoke):
        log(f"platform is {platform}, not tpu; refusing (hardware A/B)")
        return 1

    import numpy as np
    from tpu_mx import nd
    from tpu_mx.contrib import quantization as q
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.layout import default_layout

    b = args.batch
    record = {"measured_at": time.strftime("%Y-%m-%dT%H:%M:%S+0000",
                                           time.gmtime()),
              "platform": platform, "model": "resnet50_v1 (NHWC, s2d)",
              "batch": b, "iters": args.iters, "arms": {}}
    # same-platform merge: tpu artifacts never absorb cpu smoke rows
    merge_prior_sections(record, prior, ("arms",),
                         require_platform=platform)

    log(f"building resnet50_v1 batch={b}...")
    rng = np.random.RandomState(0)
    with default_layout("NHWC"):
        net = vision.resnet50_v1(classes=1000, stem="s2d")
    net.initialize(init="xavier")
    x_np = rng.rand(b, 224, 224, 3).astype(np.float32)
    x = nd.array(x_np)
    net(x)  # finalize deferred shapes
    net.hybridize()
    fetch = lambda o: fetch_sync(o._data[0, 0])

    def arm(name, fn, params):
        log(f"{name}: compiling + timing...")
        try:
            dt = _timed(fn, fetch, args.warmup, args.iters)
            row = {"img_per_s": round(b / dt, 2),
                   "ms_per_batch": round(dt * 1e3, 2),
                   "param_bytes": _param_bytes(params)}
        except Exception as e:
            row = {"error": f"{type(e).__name__}: {e}"[:400]}
            log(f"  {name} failed: {row['error']}")
        # self-describing rows (artifact_protocol contract): merged-in
        # rows may come from runs with different --batch/--iters, and the
        # row is the only place that provenance survives the merge
        row["batch"] = b
        row["iters"] = args.iters
        row["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S+0000",
                                           time.gmtime())
        record["arms"][name] = row
        write_atomic(args.out, record)
        return row

    f32 = arm("f32", lambda: net(x), net.collect_params())

    net.cast("bfloat16")
    xb = nd.cast(x, "bfloat16")
    net.hybridize()  # re-trace at the new dtype
    bf16 = arm("bf16", lambda: net(xb), net.collect_params())

    # quantize from a fresh f32 copy (cast-back corrupts calibration)
    with default_layout("NHWC"):
        qsrc = vision.resnet50_v1(classes=1000, stem="s2d")
    qsrc.initialize(init="xavier")
    calib = nd.array(x_np[:16])
    qsrc(calib)
    log("quantizing (calibration)...")
    qnet = q.quantize_net(qsrc, calib_data=calib)
    int8 = arm("int8", lambda: qnet(x), qsrc.collect_params())
    # serving-footprint story: quantized leaf weights store 1 byte/elem
    # (scales are negligible); everything else stays float.  The arm's
    # OWN param_bytes must be the quantized footprint — reporting the
    # float source net's bytes there would claim int8 saves nothing.
    # Skip entirely on a failed arm: an error row must not carry a
    # fabricated footprint.
    if "error" not in int8:
        try:
            wq = sum(p._data.size
                     for name, p in qsrc.collect_params().items()
                     if name.endswith("weight") and p._data is not None)
            float_bytes = int8.get("param_bytes", 0)
            int8["param_bytes_float_source"] = float_bytes
            int8["param_bytes"] = int(wq + max(float_bytes - wq * 4, 0))
            int8["param_bytes_note"] = ("int8 weights at 1 B/elem + "
                                        "non-quantized leaves at source "
                                        "dtype (analytic; wrapper storage "
                                        "is closure-internal)")
            write_atomic(args.out, record)
        except Exception as e:
            log(f"int8 footprint calc failed: {type(e).__name__}: {e}")

    if "img_per_s" in bf16 and "img_per_s" in int8:
        record["int8_vs_bf16"] = round(int8["img_per_s"] /
                                       bf16["img_per_s"], 4)
        record["verdict"] = (
            "int8 FASTER than bf16" if record["int8_vs_bf16"] > 1.0 else
            "int8 SLOWER than bf16 - its honest value on TPU is weight "
            "memory/serving footprint, not throughput (divergence #21)")
        write_atomic(args.out, record)
        log(f"int8 vs bf16: {record['int8_vs_bf16']:.3f}x "
            f"({record['verdict']})")
    log(f"done: {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
