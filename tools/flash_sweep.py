"""Short-T flash-kernel block sweep vs XLA dense (VERDICT r4 ask#4).

The r4 A/B measured the Pallas flash kernel losing to XLA dense by
34%/25%/5% at T=128/256/512 (fwd+bwd, causal, bf16) and the auto
dispatch was pinned to dense at kv_len <= TPUMX_DENSE_MAX_KV=512.  This
tool answers "is that overhead tunable or structural?" on chip:

  - for each T it measures XLA dense and the flash kernel at every valid
    (block_q, block_k) combination (the kernel's only tuning surface);
  - constant token budget across T (B = tokens/T) so rows are comparable;
  - per-combo rows merge into FLASH_SWEEP_<round>.json immediately
    (artifact-protocol semantics: partial reruns merge, a TPU-less run
    refuses to clobber).

Note the structural expectation: at T <= 512 `_pick_block` already
collapses to a single (T, T) block per b*h grid cell, so there is
nothing smaller to pipeline — if no combo closes the gap, the honest
outcome is "dense below the crossover is final" and the dispatch default
stands with this artifact as the evidence.

    python tools/flash_sweep.py [--lens 128,256,512,1024]
        [--tokens 65536] [--heads 12] [--dim 64] [--iters 10]
"""
from __future__ import annotations

import argparse
import itertools
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# exported for tpu_watch's done-predicate (the drift-proofing pattern:
# hand-maintained copies of a tool's coverage once cost a 90-min rerun
# loop); module top stays stdlib-only so the watcher can import it
DEFAULT_LENS = (128, 256, 512, 1024)


def log(msg):
    print(f"[flash_sweep {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def measure(attn_fn, b, h, t, d, iters):
    import jax
    import jax.numpy as jnp
    from tpu_mx.runtime import fetch_sync
    key = jax.random.PRNGKey(0)
    qk, kk, vk = jax.random.split(key, 3)
    q = jax.random.normal(qk, (b, h, t, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
    v = jax.random.normal(vk, (b, h, t, d), jnp.bfloat16)

    def loss_and_grads(q, k, v):
        return jax.value_and_grad(
            lambda q, k, v: attn_fn(q, k, v).astype(jnp.float32).mean(),
            argnums=(0, 1, 2))(q, k, v)

    step = jax.jit(loss_and_grads)
    fetch_sync(step(q, k, v)[0])                  # compile + settle
    t0 = time.perf_counter()
    l = None
    for _ in range(iters):
        l, _ = step(q, k, v)
    fetch_sync(l)
    dt = (time.perf_counter() - t0) / iters
    return {"ms_per_step": round(dt * 1e3, 3),
            "tok_per_s": int(b * t / dt)}


def main():
    from artifact_protocol import (artifact, load_prior,
                                   merge_prior_sections, refuses_clobber,
                                   write_atomic)
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=artifact("FLASH_SWEEP"))
    ap.add_argument("--lens",
                    default=",".join(str(t) for t in DEFAULT_LENS))
    ap.add_argument("--tokens", type=int, default=65536,
                    help="constant token budget; B = tokens / T")
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="tiny-shape CPU pass through the full code path "
                         "(interpret-mode kernel; r4 lesson: never ship a "
                         "chip tool whose Python path never ran)")
    args = ap.parse_args()

    import jax
    if args.cpu_smoke:
        jax.config.update("jax_platforms", "cpu")
        if args.lens == ap.get_default("lens"):
            args.lens = "128,256"
        args.tokens, args.iters = 512, 1
        if args.out == artifact("FLASH_SWEEP"):
            args.out = "/tmp/flash_sweep_smoke.json"
    from tpu_mx.runtime import enable_shared_compilation_cache
    enable_shared_compilation_cache()
    platform = jax.devices()[0].platform
    prior = load_prior(args.out)
    if refuses_clobber(prior, platform) or \
            (platform != "tpu" and not args.cpu_smoke):
        log(f"platform is {platform}, not tpu; refusing (hardware sweep)")
        return 1
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import mha_flash_attention

    h, d = args.heads, args.dim
    geom = {"H": h, "D": d, "iters": args.iters, "causal": True}
    record = {"measured_at": time.strftime("%Y-%m-%dT%H:%M:%S+0000",
                                           time.gmtime()),
              "platform": platform,
              "config": "fwd+bwd, causal, bf16, loss-fetch-bounded, "
                        "constant token budget across T",
              "sweep": {}}
    # merge only same-platform priors: a tpu artifact never absorbs cpu
    # smoke rows, and the smoke path still exercises the merge machinery
    merge_prior_sections(record, prior, ("sweep",),
                         require_platform=platform)

    def dense(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / (d ** 0.5)
        tq = s.shape[-2]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tq)[None, :]
        p = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    for t in [int(x) for x in args.lens.split(",") if x.strip()]:
        b = max(1, args.tokens // t)
        row = dict(geom, B=b, T=t,
                   measured_at=time.strftime("%Y-%m-%dT%H:%M:%S+0000",
                                             time.gmtime()))
        log(f"T={t} B={b}: dense...")
        try:
            row["dense"] = measure(dense, b, h, t, d, args.iters)
        except Exception as e:
            row["dense"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        # every valid block combo <= T (the kernel clamps anyway; dedup)
        combos = sorted({(min(bq, t), min(bk, t))
                         for bq in (128, 256, 512)
                         for bk in (128, 256, 512, 1024)})
        row["flash"] = {}
        best = None
        for bq, bk in combos:
            tag = f"bq{bq}_bk{bk}"
            log(f"T={t} B={b}: flash {tag}...")
            try:
                r = measure(lambda q, k, v: mha_flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk),
                    b, h, t, d, args.iters)
            except Exception as e:
                r = {"error": f"{type(e).__name__}: {e}"[:300]}
            row["flash"][tag] = r
            if "tok_per_s" in r and (best is None or
                                     r["tok_per_s"] > best[1]):
                best = (tag, r["tok_per_s"])
            record["sweep"][f"T={t}"] = row
            write_atomic(args.out, record)
        if best and "tok_per_s" in row.get("dense", {}):
            row["best_flash"] = best[0]
            row["flash_vs_dense"] = round(best[1] /
                                          row["dense"]["tok_per_s"], 4)
            log(f"T={t}: best flash {best[0]} = "
                f"{row['flash_vs_dense']:.3f}x dense")
        # the watcher's resume contract keys off this: a wedge mid-row
        # leaves complete unset and the stage re-runs (merge keeps the
        # finished combos)
        row["complete"] = True
        record["sweep"][f"T={t}"] = row
        write_atomic(args.out, record)
    log(f"done: {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
