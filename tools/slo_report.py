#!/usr/bin/env python
"""Render live-window SLO state, burn rate, and worst-request breakdowns.

The serving runtime's telemetry snapshots (TPUMX_TELEMETRY JSONL) carry,
on every counter/histogram record, a ``window`` sub-object — the
trailing-window state the SLO engine reads live (tpu_mx/telemetry.py).
This tool is the jax-less ops view over that data:

- **Windowed latency state**: per histogram series, the window's sample
  count and p50/p90/p99 bucket-merge estimates (the same math the live
  monitor uses — ``telemetry.quantile_from_cumulative``);
- **SLO targets**: each ``--slo`` spec (default: the serving pair
  ``ttft_p99 < 500ms`` / ``itl_p99 < 50ms``; grammar:
  ``telemetry.parse_slo_spec``) evaluated against the window —
  estimate vs threshold, attainment, error-budget burn rate, OK/BREACH;
- **Live monitor gauges**: the ``serve.slo_*`` series a running
  ``serving.SLOMonitor`` published, when armed;
- **Worst requests** (``--box <prefix>-blackbox.json``): the
  ``serve.request_timeline`` events from a flight-recorder black box,
  sorted by latency, each decomposed into its typed phases
  (queue_wait/prefill/decode_gap/restart_penalty/defer_stall) with
  percentages — "which phase of this slow request ate the budget";
- **Per-tenant state** (ISSUE 12): for every tenant-labeled series of a
  target's histogram (``serve.itl_seconds{tenant=...}`` — bounded
  labels, tpu_mx/serving/tenancy.py), the window quantile, attainment
  and burn rate, plus each tenant's worst request by latency with its
  phase breakdown — "WHICH tenant's budget is burning, and on what".

``--validate`` schema-gates every telemetry record (including the
window sub-objects) against the catalog, every box event against
``tracing.KNOWN_EVENTS``, and every request timeline against the
attribution invariant (phases sum to the recorded latency within 5%).
Exit status: 0 ok, 1 validation failure, 2 unreadable input — the same
contract as tools/blackbox_report.py, enforced by the ``obs``/``serve``
CI tiers.

The tpu_mx modules are loaded standalone from their files — this tool
NEVER imports the ``tpu_mx`` package (which would boot jax); it must
work on a machine with no accelerator stack at all.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# share blackbox_report's standalone loader (load tpu_mx/<name>.py by
# file path, NEVER import the package — which would boot jax) instead of
# keeping a third copy of the mechanism in sync
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from blackbox_report import load_module  # noqa: E402


def read_series(path, telemetry, validate=False):
    """{(name, labels_json): last_record} from a cumulative-snapshot
    JSONL file, plus the validation error list."""
    series, errors = {}, []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                errors.append(f"line {lineno}: not JSON: {e}")
                continue
            if validate:
                try:
                    telemetry.validate_record(rec)
                except ValueError as e:
                    errors.append(f"line {lineno}: {e}")
                    continue
                if rec["name"] not in telemetry.KNOWN_METRICS:
                    errors.append(
                        f"line {lineno}: unknown metric name "
                        f"{rec['name']!r} — not in telemetry.KNOWN_METRICS")
                    continue
            key = (rec.get("name"),
                   json.dumps(rec.get("labels", {}), sort_keys=True))
            series[key] = rec
    return series, errors


def _label(name, labels_json):
    labels = json.loads(labels_json)
    if not labels:
        return name
    return name + "{%s}" % ",".join(f"{k}={v}"
                                    for k, v in sorted(labels.items()))


def _ms(v):
    return "-" if v is None else f"{v * 1e3:.3f}"


def render_windows(series, telemetry):
    """The windowed-histogram table: per series with window samples,
    count and p50/p90/p99 estimates in ms."""
    lines = ["Windowed latency state (trailing-window bucket-merge "
             "estimates, ms):",
             "  %-44s %8s %7s %10s %10s %10s" %
             ("Series", "win(s)", "count", "p50", "p90", "p99")]
    shown = 0
    for (name, lj), rec in sorted(series.items()):
        if rec.get("type") != "histogram":
            continue
        win = rec.get("window")
        if not win or not win.get("count"):
            continue
        shown += 1
        q = {}
        for p in (0.50, 0.90, 0.99):
            q[p] = telemetry.quantile_from_cumulative(
                win["buckets"], p, vmin=win.get("min"),
                vmax=win.get("max"))
        lines.append("  %-44s %8g %7d %10s %10s %10s" % (
            _label(name, lj), win.get("seconds", 0), win["count"],
            _ms(q[0.50]), _ms(q[0.90]), _ms(q[0.99])))
    if not shown:
        lines.append("  (no histogram series with window samples — "
                     "pre-window snapshot, or the run was idle)")
    return lines


def render_slos(series, telemetry, specs):
    """Evaluate each --slo spec against its histogram's window."""
    lines = ["SLO targets (evaluated over each series' trailing "
             "window):",
             "  %-28s %12s %12s %11s %9s %8s" %
             ("Target", "estimate", "threshold", "attainment", "burn",
              "status")]
    for spec in specs:
        try:
            d = telemetry.parse_slo_spec(spec)
        except ValueError as e:
            lines.append(f"  {spec!r}: {e}")
            continue
        rec = series.get((d["metric"], "{}"))
        win = (rec or {}).get("window")
        if not win or not win.get("count"):
            lines.append("  %-28s %12s %12s %11s %9s %8s" % (
                d["name"], "-", _ms(d["threshold_seconds"]), "-", "-",
                "no data"))
            continue
        est = telemetry.quantile_from_cumulative(
            win["buckets"], d["quantile"], vmin=win.get("min"),
            vmax=win.get("max"))
        att = telemetry.fraction_le_from_cumulative(
            win["buckets"], d["threshold_seconds"], vmin=win.get("min"),
            vmax=win.get("max"))
        burn = (1.0 - att) / (1.0 - d["objective"])
        lines.append("  %-28s %9s ms %9s ms %11.4f %9.2f %8s" % (
            d["name"], _ms(est), _ms(d["threshold_seconds"]), att, burn,
            "BREACH" if burn >= 1.0 else "OK"))
    return lines


# the live monitor's empty-window sentinel (serving.slo.NO_DATA): the
# estimate/attainment gauges publish -1.0 when their window holds no
# samples — a legitimate state, rendered as "n/a", never as a negative
# latency or a negative burn rate in the per-tenant gauge rows
NO_DATA = -1.0


def render_monitor_gauges(series):
    """The serve.slo_* gauges a live SLOMonitor published.  The NO_DATA
    sentinel (-1, published while an evaluation window is empty so a
    dashboard never reads a frozen stale value as live) renders as
    ``n/a``: estimates are positive and attainment lives in [0, 1], so
    -1 is unambiguously no-data, not a measurement."""
    rows = [(k, r) for k, r in sorted(series.items())
            if k[0].startswith("serve.slo_")]
    if not rows:
        return ["Live monitor gauges: (none — no SLOMonitor was armed)"]
    lines = ["Live monitor gauges (serving.SLOMonitor state at last "
             "snapshot):"]
    for (name, lj), rec in rows:
        v = rec.get("value")
        shown = "n/a (empty window)" if v == NO_DATA else "%g" % v
        lines.append("  %-56s %s" % (_label(name, lj), shown))
    return lines


def render_recovery(series, box):
    """The restart-recovery section (ISSUE 19): how restarts were paid
    for — replayed (one prefill re-establishing the committed ledger)
    vs re-decoded (the legacy prompt-replay arm's catch-up tokens) —
    plus the journal's durability counters and the per-request
    ``restart_penalty`` phase totals from the box's timelines.  The
    replay-vs-redecode split IS the zero-regeneration receipt: a
    healthy replay-arm run shows restarts > 0 with re-decoded == 0."""
    def cval(name):
        rec = series.get((name, "{}"))
        return 0 if rec is None else rec.get("value", 0)

    lines = ["Restart recovery (zero-regeneration serving):"]
    restarts = cval("serve.engine_restarts")
    if not restarts and not cval("serve.journal_requests"):
        lines.append("  (no engine restarts and no journal in this "
                     "snapshot — nothing was recovered)")
        return lines
    lines.append("  engine restarts        %d" % restarts)
    lines.append("  replayed sequences     %d  (ONE prefill each — "
                 "committed ledger kept)" % cval("serve.replay_requests"))
    lines.append("  replayed tokens        %d  (re-established by "
                 "prefill, not re-decoded)" % cval("serve.replay_tokens"))
    lines.append("  re-decoded tokens      %d  (legacy prompt-replay "
                 "catch-up work)" % cval("serve.redecode_tokens"))
    if cval("serve.journal_requests"):
        lines.append("  journal                %d request(s), %d "
                     "token(s), %d byte(s) fsync'd, %d fallback(s)" % (
                         cval("serve.journal_requests"),
                         cval("serve.journal_tokens"),
                         cval("serve.journal_bytes"),
                         cval("serve.replay_fallbacks")))
    if box is not None:
        pens = [(e["data"].get("request", "?"),
                 float(e["data"].get("restart_penalty", 0.0)))
                for e in request_timelines(box)
                if float(e["data"].get("restart_penalty", 0.0)) > 0]
        if pens:
            total = sum(p for _, p in pens)
            worst = max(pens, key=lambda rp: rp[1])
            lines.append(
                "  restart_penalty        %d request(s) paid %.2fms "
                "total; worst %s at %.2fms" % (
                    len(pens), total * 1e3, worst[0], worst[1] * 1e3))
        else:
            lines.append("  restart_penalty        (no request in the "
                         "box paid a restart penalty)")
    return lines


def render_tenants(series, telemetry, specs, box, phases):
    """The per-tenant section: each target evaluated against every
    tenant-labeled series' window (quantile estimate, attainment, burn,
    status), then each tenant's worst recorded request with its phase
    breakdown.  Tenant labels are already cardinality-bounded at the
    source (tenancy.label_for: the overflow label aggregates the long
    tail)."""
    targets = []
    for spec in specs:
        try:
            targets.append(telemetry.parse_slo_spec(spec))
        except ValueError:
            continue
    tenants = set()
    for (name, lj), rec in series.items():
        labels = json.loads(lj)
        if rec.get("type") == "histogram" and "tenant" in labels:
            tenants.add(labels["tenant"])
    by_tenant = {}
    if box is not None:
        for e in request_timelines(box):
            t = e["data"].get("tenant")
            if t is not None:
                tenants.add(t)
                by_tenant.setdefault(t, []).append(e)
    if not tenants:
        return ["Per-tenant SLO state: (no tenant-labeled series — "
                "single-tenant run, or pre-tenancy snapshot)"]
    lines = ["Per-tenant SLO state (window estimates per tenant label):",
             "  %-10s %-24s %7s %12s %11s %9s %8s" %
             ("Tenant", "Target", "count", "estimate", "attainment",
              "burn", "status")]
    for tenant in sorted(tenants):
        for d in targets:
            key = (d["metric"],
                   json.dumps({"tenant": tenant}, sort_keys=True))
            win = (series.get(key) or {}).get("window")
            if not win or not win.get("count"):
                lines.append("  %-10s %-24s %7s %12s %11s %9s %8s" % (
                    tenant, d["name"], 0, "-", "-", "-", "no data"))
                continue
            est = telemetry.quantile_from_cumulative(
                win["buckets"], d["quantile"], vmin=win.get("min"),
                vmax=win.get("max"))
            att = telemetry.fraction_le_from_cumulative(
                win["buckets"], d["threshold_seconds"],
                vmin=win.get("min"), vmax=win.get("max"))
            burn = (1.0 - att) / (1.0 - d["objective"])
            lines.append("  %-10s %-24s %7d %9s ms %11.4f %9.2f %8s" % (
                tenant, d["name"], win["count"], _ms(est), att, burn,
                "BREACH" if burn >= 1.0 else "OK"))
        worst = sorted(by_tenant.get(tenant, ()),
                       key=lambda e: -float(e["data"].get("latency", 0.0)))
        if worst:
            d = worst[0]["data"]
            lat = float(d.get("latency", 0.0))
            parts = []
            for p in phases:
                v = float(d.get(p, 0.0))
                if v > 0:
                    pct = 100.0 * v / lat if lat > 0 else 0.0
                    parts.append(f"{p} {v * 1e3:.2f}ms ({pct:.0f}%)")
            lines.append(
                "    worst request: %-12s %8.2fms %-8s cached=%s"
                % (d.get("request", "?"), lat * 1e3,
                   d.get("outcome", "?"), d.get("cached_tokens", 0)))
            lines.append("      " + (" + ".join(parts) if parts
                                     else "(empty)"))
    return lines


def timeline_phases(tracing):
    """The attribution phases, in render order, derived from the
    ``serve.request_timeline`` event schema — NOT hand-copied from
    tpu_mx/serving/timeline.py, so a new phase can never make this
    tool's invariant re-check under-count and fail correct data."""
    schema = tracing.KNOWN_EVENTS["serve.request_timeline"]
    return tuple(k for k, t in schema.items()
                 if t == "float" and k not in ("latency", "ttft"))


def request_timelines(box):
    """The serve.request_timeline events from a black-box document."""
    return [e for e in box.get("events", [])
            if e.get("event") == "serve.request_timeline"
            and isinstance(e.get("data"), dict)]


def render_worst_requests(box, top, phases):
    """Top-N requests by latency, each with its phase breakdown."""
    tls = sorted(request_timelines(box),
                 key=lambda e: -float(e["data"].get("latency", 0.0)))
    lines = [f"Worst requests by latency (top {top} of {len(tls)} "
             "recorded timelines):"]
    if not tls:
        lines.append("  (no serve.request_timeline events in the box)")
        return lines
    for e in tls[:top]:
        d = e["data"]
        lat = float(d.get("latency", 0.0))
        parts = []
        for p in phases:
            v = float(d.get(p, 0.0))
            if v > 0:
                pct = 100.0 * v / lat if lat > 0 else 0.0
                parts.append(f"{p} {v * 1e3:.2f}ms ({pct:.0f}%)")
        lines.append(
            "  %-12s %8.2fms  %-8s tok=%-3s requeues=%s defers=%s"
            % (d.get("request", "?"), lat * 1e3, d.get("outcome", "?"),
               d.get("tokens", "?"), d.get("requeues", "?"),
               d.get("defers", "?")))
        lines.append("    " + (" + ".join(parts) if parts else "(empty)"))
    return lines


def validate_timelines(box, phases, tolerance):
    """The attribution invariant, re-checked offline: each recorded
    timeline's phases must sum to its latency within tolerance
    (``telemetry.ATTRIBUTION_TOLERANCE`` — the serve CI tier's bar)."""
    errors = []
    for e in request_timelines(box):
        d = e["data"]
        lat = float(d.get("latency", 0.0))
        total = sum(float(d.get(p, 0.0)) for p in phases)
        tol = max(tolerance * lat, 1e-3)
        if abs(total - lat) > tol:
            errors.append(
                f"request {d.get('request', '?')}: phases sum to "
                f"{total * 1e3:.3f}ms but latency is {lat * 1e3:.3f}ms "
                f"(tolerance {tol * 1e3:.3f}ms)")
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("file", help="TPUMX_TELEMETRY JSONL snapshot file")
    ap.add_argument("--box", default=None,
                    help="a <prefix>-blackbox.json dump: adds the "
                         "worst-request phase-breakdown section")
    ap.add_argument("--slo", action="append", default=[],
                    help="SLO spec to evaluate, e.g. 'itl_p99 < 50ms' "
                         "(repeatable; default: the serving pair)")
    ap.add_argument("--top", type=int, default=5,
                    help="worst requests to show (default 5)")
    ap.add_argument("--validate", action="store_true",
                    help="fail on schema violations or attribution "
                         "invariant breaks")
    opts = ap.parse_args(argv)
    telemetry = load_module("telemetry")
    try:
        series, errors = read_series(opts.file, telemetry,
                                     validate=opts.validate)
    except OSError as e:
        print(f"slo_report: cannot read {opts.file}: {e}",
              file=sys.stderr)
        return 2
    box = None
    tracing = load_module("tracing")
    if opts.box:
        try:
            with open(opts.box, encoding="utf-8") as f:
                box = json.load(f)
        except (OSError, ValueError) as e:
            print(f"slo_report: cannot read {opts.box}: {e}",
                  file=sys.stderr)
            return 2

    specs = opts.slo or list(telemetry.DEFAULT_SLOS)
    out = [f"SLO report: {opts.file}", ""]
    out.extend(render_windows(series, telemetry))
    out.append("")
    out.extend(render_slos(series, telemetry, specs))
    out.append("")
    out.extend(render_monitor_gauges(series))
    out.append("")
    out.extend(render_recovery(series, box))
    out.append("")
    out.extend(render_tenants(series, telemetry, specs, box,
                              timeline_phases(tracing)))
    if box is not None:
        out.append("")
        out.extend(render_worst_requests(box, opts.top,
                                         timeline_phases(tracing)))
    print("\n".join(out))

    if opts.validate:
        if box is not None:
            try:
                tracing.validate_blackbox(box)
            except ValueError as e:
                errors.append(f"box: {e}")
            errors.extend(f"box: {e}" for e in validate_timelines(
                box, timeline_phases(tracing),
                telemetry.ATTRIBUTION_TOLERANCE))
        if not series:
            errors.append("file contains no telemetry records")
        if errors:
            print("VALIDATION FAILED:", file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
        print(f"schema OK: {len(series)} series"
              + (f", {len(request_timelines(box))} request timeline(s)"
                 if box is not None else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
