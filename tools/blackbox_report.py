#!/usr/bin/env python
"""Render a flight-recorder black box as a human-readable post-mortem.

A supervised run dumps ``<prefix>-blackbox.json`` (tpu_mx/tracing.py) on
every recovery decision — watchdog fire, NaN rollback, restart, degrade —
and on SIGTERM preemption.  This tool reconstructs what happened:

- the **timeline**: every recorded event with its step-scoped trace
  context ``(epoch, step, generation)`` and relative timestamp;
- the **recovery chains**: for each injected/observed fault, the
  correlated ``injection -> detection -> supervisor decision`` line
  (e.g. ``epoch 2 step 3: chaos hang injected -> watchdog fired at 20.0s
  -> classified transient -> restart #1 from epoch 2``), linked by the
  shared trace context;
- the **telemetry snapshot** taken at dump time (recovery counters and
  latency histograms);
- the **environment fingerprint** (host, pid, python, TPUMX_*/JAX_* env).

``--validate`` additionally schema-checks the box: the format tag, every
event against ``tracing.KNOWN_EVENTS`` (names AND payload field types),
and every telemetry record against the telemetry schema + catalog.
Exit status: 0 ok, 1 validation failure, 2 unreadable input.

The tpu_mx modules are loaded standalone from their files — this tool
NEVER imports the ``tpu_mx`` package (which would boot jax) just to read
a JSON post-mortem; it must work on a machine with no accelerator stack
at all.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_module(name):
    """Load tpu_mx/<name>.py WITHOUT importing the tpu_mx package (both
    tracing.py and telemetry.py are stdlib-only at module level by
    contract)."""
    path = os.path.join(REPO, "tpu_mx", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_tpumx_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ctx(e):
    ep = e.get("epoch")
    st = e.get("step")
    return "e%s/s%s/g%s" % ("-" if ep is None else ep,
                            "-" if st is None else st,
                            e.get("generation", "-"))


def _payload(e):
    data = e.get("data")
    if not isinstance(data, dict):  # malformed: render, don't crash — a
        return "(malformed payload)"  # post-mortem reader needs the rest
    return " ".join(f"{k}={_fmt(v)}" for k, v in sorted(data.items()))


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_timeline(events):
    if not events:
        return ["  (no events recorded — was TPUMX_TRACING=0 set?)"]
    t0 = events[0].get("ts", 0)
    lines = []
    for e in events:
        lines.append("  %+10.3fs  %-10s %-26s %s" % (
            e.get("ts", 0) - t0, _ctx(e), e.get("event", "?"),
            _payload(e)))
    return lines


# fault events that OPEN a recovery chain; keyed by how the chain line
# describes them
_FAULT_EVENTS = ("chaos.inject", "supervisor.watchdog_fire",
                 "supervisor.sentinel_skip")


def recovery_chains(events):
    """One ``injection -> detection -> decision`` line per observed
    fault, linked by the shared (epoch, generation) trace context (the
    decision for a step-K fault can land at step K+1 — e.g. a NaN streak
    whose divergence is declared a batch later — so the step is reported
    but not used for the join)."""
    chains = []
    for i, e in enumerate(events):
        if e.get("event") != "chaos.inject":
            continue
        key = (e.get("epoch"), e.get("generation"))
        data = e.get("data") if isinstance(e.get("data"), dict) else {}
        parts = [f"chaos {data.get('kind', '?')} injected"]
        for later in events[i + 1:]:
            if (later.get("epoch"), later.get("generation")) != key:
                continue
            name = later.get("event")
            d = later.get("data")
            if not isinstance(d, dict):
                d = {}
            if name == "supervisor.watchdog_fire":
                parts.append("watchdog fired at "
                             f"{_fmt(d.get('deadline_seconds', '?'))}s")
            elif name == "supervisor.sentinel_skip":
                parts.append("sentinel skipped batch "
                             f"(bad streak {d.get('consecutive_bad', '?')})")
            elif name == "supervisor.classify":
                parts.append(f"classified {d.get('kind', '?')} "
                             f"({d.get('error', '?')})")
            elif name == "supervisor.restart":
                parts.append(f"restart #{d.get('n', '?')} from epoch "
                             f"{d.get('resume_epoch', '?')}")
                break
            elif name == "supervisor.rollback":
                parts.append(f"rollback #{d.get('n', '?')} to epoch "
                             f"{d.get('resume_epoch', '?')}")
                break
            elif name == "supervisor.degrade":
                parts.append(f"degraded ({d.get('budget', '?')} budget "
                             "exhausted)")
                break
            elif name == "checkpoint.preemption":
                parts.append(f"preempted (signal {d.get('signum', '?')}, "
                             f"emergency save_ok={d.get('save_ok', '?')})")
                break
            # serving runtime decisions (tpu_mx/serving/, ISSUE 8): the
            # engine-step context has no epoch (None) but the same
            # generation join applies — a decode fault and the engine
            # restart it provoked share (step, generation)
            elif name == "serve.reject":
                parts.append("admission rejected "
                             f"({d.get('reason', '?')}, "
                             f"request {d.get('request', '?')})")
                break
            elif name == "serve.restart":
                parts.append(f"engine restart #{d.get('n', '?')} "
                             f"(requeued {d.get('requeued', '?')} "
                             "in-flight requests)")
                break
        chains.append("  epoch %s step %s: %s" % (
            "-" if e.get("epoch") is None else e["epoch"],
            "-" if e.get("step") is None else e["step"],
            " -> ".join(parts)))
    return chains


def render_telemetry(records):
    lines = []
    for rec in sorted(records, key=lambda r: (r.get("name", ""),
                                              str(r.get("labels", {})))):
        name = rec.get("name", "?")
        labels = rec.get("labels")
        if labels:
            name += "{%s}" % ",".join(f"{k}={v}"
                                      for k, v in sorted(labels.items()))
        if rec.get("type") == "histogram":
            lines.append("  %-50s count=%s sum=%.6gs"
                         % (name, rec.get("value"), rec.get("sum", 0.0)))
        else:
            lines.append("  %-50s %s" % (name, _fmt(rec.get("value"))))
    return lines or ["  (no telemetry in the box)"]


def render(doc, path):
    ctx = doc.get("context", {})
    st = doc.get("stats", {})
    env = doc.get("environment", {})
    out = [f"Black box: {path}",
           f"  format:  {doc.get('format')}",
           f"  reason:  {doc.get('reason') or '(unspecified)'}",
           f"  written: {doc.get('written_at')}",
           f"  run:     {ctx.get('run_id')}  (context at dump: "
           f"epoch={ctx.get('epoch')} step={ctx.get('step')} "
           f"generation={ctx.get('generation')})",
           f"  ring:    {len(doc.get('events', []))} event(s) held, "
           f"capacity {st.get('capacity')}, {st.get('dropped', 0)} "
           f"dropped ({st.get('emitted', 0)} emitted total)", ""]
    chains = recovery_chains(doc.get("events", []))
    if chains:
        out.append("Recovery chains (injection -> detection -> decision, "
                   "correlated by shared trace context):")
        out.extend(chains)
        out.append("")
    out.append("Timeline:")
    out.extend(render_timeline(doc.get("events", [])))
    out.append("")
    out.append("Telemetry at dump time:")
    out.extend(render_telemetry(doc.get("telemetry", [])))
    out.append("")
    out.append("Environment:")
    out.append(f"  host={env.get('hostname')} pid={env.get('pid')} "
               f"python={env.get('python')} platform={env.get('platform')} "
               f"jax={env.get('jax')}")
    for k, v in sorted((env.get("env") or {}).items()):
        out.append(f"  {k}={v}")
    return "\n".join(out)


def validate(doc, tracing, telemetry):
    """Every schema violation as a string (empty = valid)."""
    errors = []
    try:
        tracing.validate_blackbox(doc)
    except ValueError as e:
        errors.append(str(e))
    for i, rec in enumerate(doc.get("telemetry") or []):
        try:
            telemetry.validate_record(rec)
        except ValueError as e:
            errors.append(f"telemetry[{i}]: {e}")
            continue
        if rec["name"] not in telemetry.KNOWN_METRICS:
            errors.append(f"telemetry[{i}]: unknown metric name "
                          f"{rec['name']!r} — not in KNOWN_METRICS")
    return errors


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("file", help="a <prefix>-blackbox.json dump")
    ap.add_argument("--validate", action="store_true",
                    help="fail on schema violations (event names/payload "
                         "types outside tracing.KNOWN_EVENTS, malformed "
                         "telemetry records)")
    opts = ap.parse_args(argv)
    try:
        with open(opts.file, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"blackbox_report: cannot read {opts.file}: {e}",
              file=sys.stderr)
        return 2
    tracing = load_module("tracing")
    print(render(doc, opts.file))
    if opts.validate:
        telemetry = load_module("telemetry")
        errors = validate(doc, tracing, telemetry)
        if errors:
            print("VALIDATION FAILED:", file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
        print(f"schema OK: {len(doc.get('events', []))} event(s), "
              f"{len(doc.get('telemetry', []))} telemetry record(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
