"""Collective bandwidth measurement over the device mesh
(REF:tools/bandwidth/measure.py — the reference measured KVStore push/pull
bandwidth between devices/servers; the TPU-native analog measures the XLA
collectives that replaced them: psum, all_gather, reduce_scatter,
ppermute over the ICI/DCN mesh).

    python tools/bandwidth.py --sizes 1,4,16 --axis dp
    python tools/bandwidth.py --devices 8        # CPU: virtualize 8

Prints one JSON line per (collective, size): algorithmic bandwidth
GB/s = bytes_moved / time, where bytes_moved uses the standard ring-
algorithm accounting (2·(n-1)/n·size for allreduce, (n-1)/n·size for
all_gather/reduce_scatter, size for ppermute).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1,4,16,64",
                    help="per-device payload MB, comma separated")
    ap.add_argument("--axis", default="dp")
    ap.add_argument("--devices", type=int, default=0,
                    help="virtualize N CPU devices if fewer are present")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    if args.devices:
        flag = f"--xla_force_host_platform_device_count={args.devices}"
        if "--xla_force_host_platform_device_count" not in os.environ.get(
                "XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    import jax
    if args.devices > 1:
        # must happen BEFORE the first jax.devices() query — that call
        # initializes and pins the backend (same rule as __graft_entry__)
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    from tpu_mx.runtime import fetch_sync
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_mx.parallel import make_mesh

    n = args.devices or len(jax.devices())
    mesh = make_mesh({args.axis: n}, devices=jax.devices()[:n])
    ax = args.axis
    perm = [(i, (i + 1) % n) for i in range(n)]

    # per-device bytes moved, as a multiple of the per-device INPUT shard:
    # ring allreduce 2(n-1)/n of the (sharded) input, ring all_gather
    # sends/receives (n-1) shard-sized blocks, reduce_scatter (n-1)/n,
    # ppermute exactly one shard
    colls = {
        "psum": (lambda x: lax.psum(x, ax), 2.0 * (n - 1) / n),
        "all_gather": (lambda x: lax.all_gather(x, ax), float(n - 1)),
        "reduce_scatter": (
            lambda x: lax.psum_scatter(x, ax, tiled=True), (n - 1) / n),
        "ppermute": (lambda x: lax.ppermute(x, ax, perm), 1.0),
    }

    for mb in (float(s) for s in args.sizes.split(",")):
        elems_per_dev = max(1, int(mb * 1e6 / 4))
        x = jnp.ones((n * elems_per_dev,), jnp.float32)
        x = jax.device_put(x, NamedSharding(mesh, P(ax)))
        for name, (fn, factor) in colls.items():
            sm = shard_map(fn, mesh=mesh, in_specs=P(ax),
                           out_specs=(P(None) if name == "all_gather"
                                      else P(ax)), check_rep=False)
            jitted = jax.jit(sm)
            # bound by a host fetch (tpu_mx.runtime.fetch_sync), not
            # block_until_ready, which lies on the tunneled axon backend
            fetch_sync(jitted(x)[:1])  # compile+warm
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = jitted(x)
            fetch_sync(out[:1])
            dt = (time.perf_counter() - t0) / args.iters
            moved = factor * elems_per_dev * 4
            print(json.dumps({
                "collective": name, "axis": ax, "devices": n,
                "payload_mb_per_device": round(mb, 3),
                "time_ms": round(dt * 1e3, 3),
                "alg_bandwidth_gbps": round(moved / dt / 1e9, 3),
            }), flush=True)


if __name__ == "__main__":
    main()
