"""Real-chip validation sweep for the Pallas/kernel tail (VERDICT r3 ask#2).

Round 3 landed in-kernel attention bias, ring inner chunking, the dropout
seed-fold fix, and the BERT remat path AFTER the tunnel wedged — none of it
has ever executed on TPU silicon, and round 2 proved interpret-mode green
is not chip green (real Mosaic enforces PRNG limits the CPU interpreter
does not).  This runner executes each of those paths on `jax.devices()[0]`
of a real TPU backend and records a per-check pass/fail artifact
(TPU_VALIDATION_<round>.json) for the judge.

Run via tools/tpu_watch.py the moment the tunnel is up, or by hand:
    python tools/tpu_validate.py [--out PATH] [--skip-bert]

Each check is isolated: one Mosaic rejection must not mask the others.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(f"[validate {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def _dense_ref(q, k, v, causal=False, valid_length=None, bias=None):
    """O(T²) reference attention in f32 — the oracle for every kernel
    check (same contract as kernels.flash_attention)."""
    import jax
    import jax.numpy as jnp
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if bias is not None:
        s = s + jnp.broadcast_to(bias, s.shape).astype(jnp.float32)
    t, tk = s.shape[-2], s.shape[-1]
    if causal:
        s = jnp.where(jnp.arange(t)[:, None] >= jnp.arange(tk)[None, :],
                      s, -1e30)
    if valid_length is not None:
        km = jnp.arange(tk)[None, None, None, :] < \
            jnp.asarray(valid_length)[:, None, None, None]
        s = jnp.where(km, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vf).astype(q.dtype)


def _max_err(a, b):
    import numpy as np
    return float(np.max(np.abs(np.asarray(a, np.float32) -
                               np.asarray(b, np.float32))))


def _highest_precision(fn):
    """Run an f32-oracle check under jax.default_matmul_precision('highest').

    On real TPU the DEFAULT matmul precision truncates f32 operands to
    single-pass bf16 on the MXU — both in the Pallas kernels' in-kernel
    dots (precision resolves from the jax config at trace time) and in the
    jnp oracle — so an exact-f32 comparison at tol 2e-3 fails with
    ~3-6e-3 truncation noise (the r4 first on-chip sweep failed exactly
    this way; CPU interpret mode computes true f32 and never showed it).
    Correctness checks compare true-f32 to true-f32; the bf16 checks and
    the benches keep DEFAULT, which is the production path."""
    import functools

    @functools.wraps(fn)
    def wrapped():
        import jax
        with jax.default_matmul_precision("highest"):
            return fn()
    return wrapped


def check_flash_fwd_bwd_vs_dense():
    """Flash kernel fwd+bwd vs dense oracle, f32 and bf16, causal and
    not.  The f32 legs run under matmul precision 'highest' (see
    _highest_precision); the bf16 legs DELIBERATELY keep DEFAULT — that
    is the production bench path, and wrapping them too would hide any
    DEFAULT-precision-only numeric bug."""
    import contextlib
    import jax
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import mha_flash_attention
    b, h, t, d = 2, 4, 512, 64
    key = jax.random.PRNGKey(0)
    qk, kk, vk = jax.random.split(key, 3)
    results = {}
    for dtype, tol in ((jnp.float32, 2e-3), (jnp.bfloat16, 4e-2)):
      with (jax.default_matmul_precision("highest")
            if dtype == jnp.float32 else contextlib.nullcontext()):
        q = jax.random.normal(qk, (b, h, t, d), dtype)
        k = jax.random.normal(kk, (b, h, t, d), dtype)
        v = jax.random.normal(vk, (b, h, t, d), dtype)
        for causal in (False, True):
            f = lambda q, k, v: mha_flash_attention(
                q, k, v, causal=causal).astype(jnp.float32).sum()
            g = lambda q, k, v: _dense_ref(
                q, k, v, causal=causal).astype(jnp.float32).sum()
            out = mha_flash_attention(q, k, v, causal=causal)
            ref = _dense_ref(q, k, v, causal=causal)
            e_out = _max_err(out, ref)
            gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
            gd = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
            e_grad = max(_max_err(a, b) for a, b in zip(gf, gd))
            tag = f"{jnp.dtype(dtype).name}_causal={causal}"
            results[tag] = {"out_err": e_out, "grad_err": e_grad}
            # grad tolerance is looser: sum-of-T cotangents accumulate
            if e_out > tol or e_grad > tol * 20:
                raise AssertionError(f"{tag}: out_err={e_out} "
                                     f"grad_err={e_grad} tol={tol}")
    return results


@_highest_precision
def check_flash_bias_layouts():
    """All broadcast layouts of the additive attention bias (r3 commit
    f1c476b, never chip-run): per-batch-head, shared-batch (G=H cycling),
    fully shared, and singleton-T broadcast.  fwd vs dense + d_bias."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import mha_flash_attention
    b, h, t, d = 2, 4, 256, 64
    key = jax.random.PRNGKey(1)
    qk, kk, vk, bk = jax.random.split(key, 4)
    q = jax.random.normal(qk, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
    v = jax.random.normal(vk, (b, h, t, d), jnp.float32)
    results = {}
    for shape in ((b, h, t, t), (1, h, t, t), (1, 1, t, t), (b, 1, 1, t)):
        bias = jax.random.normal(bk, shape, jnp.float32)
        out = mha_flash_attention(q, k, v, bias=bias)
        ref = _dense_ref(q, k, v, bias=bias)
        e_out = _max_err(out, ref)
        f = lambda bb: mha_flash_attention(q, k, v, bias=bb).sum()
        g = lambda bb: _dense_ref(q, k, v, bias=bb).sum()
        db_f = jax.grad(f)(bias)
        db_d = jax.grad(g)(bias)
        e_db = _max_err(db_f, db_d)
        results[str(shape)] = {"out_err": e_out, "dbias_err": e_db}
        if e_out > 2e-3 or e_db > 2e-2:
            raise AssertionError(f"bias {shape}: out_err={e_out} "
                                 f"dbias_err={e_db}")
    return results


@_highest_precision
def check_flash_dropout():
    """In-kernel attention-prob dropout (TPU PRNG; r3 seed-fold fix,
    never chip-run): determinism under the same seed, divergence across
    seeds, keep-rate sanity, finite grads, and fwd/bwd mask agreement via
    directional-derivative consistency."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import mha_flash_attention
    b, h, t, d = 2, 4, 256, 64
    rate = 0.25
    key = jax.random.PRNGKey(2)
    qk, kk, vk = jax.random.split(key, 3)
    q = jax.random.normal(qk, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
    v = jax.random.normal(vk, (b, h, t, d), jnp.float32)
    seed = jnp.array([1234], jnp.int32)
    run = lambda s: mha_flash_attention(q, k, v, dropout_rate=rate,
                                        dropout_seed=s)
    o1, o2 = run(seed), run(seed)
    if _max_err(o1, o2) != 0.0:
        raise AssertionError("same seed produced different outputs")
    o3 = run(jnp.array([999], jnp.int32))
    if _max_err(o1, o3) == 0.0:
        raise AssertionError("different seeds produced identical outputs")
    # fwd/bwd mask agreement via directional derivative in v: with the
    # mask and probs fixed, the output is LINEAR in v, so f = mean(O²) is
    # quadratic and the central difference is exact up to rounding — any
    # mismatch means the backward regenerated a different dropout mask
    u = jax.random.normal(jax.random.PRNGKey(7), v.shape, jnp.float32)
    f = lambda vv: (mha_flash_attention(q, k, vv, dropout_rate=rate,
                                        dropout_seed=seed) ** 2).mean()
    gv = jax.grad(f)(v)
    if not bool(jnp.isfinite(gv).all()):
        raise AssertionError("non-finite dropout grads")
    eps = 3e-3
    analytic = float((gv * u).sum())
    numeric = float((f(v + eps * u) - f(v - eps * u)) / (2 * eps))
    rel = abs(analytic - numeric) / max(abs(numeric), 1e-6)
    if rel > 5e-2:
        raise AssertionError(
            f"fwd/bwd dropout masks disagree: directional derivative "
            f"analytic={analytic:.6f} numeric={numeric:.6f} rel={rel:.4f}")
    # keep-rate sanity: ratio of dropped-softmax mass ≈ keep probability
    dense = _dense_ref(q, k, v)
    ratio = float(np.mean(np.asarray(o1) != np.asarray(dense)))
    return {"determinism": "ok", "grad_finite": True,
            "dir_deriv_rel_err": rel, "fraction_changed": ratio}


@_highest_precision
def check_flash_kv_valid():
    """Ragged key-padding masks (kv_valid) vs dense mask oracle."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import mha_flash_attention
    b, h, t, d = 4, 2, 512, 64
    key = jax.random.PRNGKey(3)
    qk, kk, vk = jax.random.split(key, 3)
    q = jax.random.normal(qk, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
    v = jax.random.normal(vk, (b, h, t, d), jnp.float32)
    vl = jnp.array([512, 300, 128, 17], jnp.int32)
    out = mha_flash_attention(q, k, v, valid_length=vl)
    ref = _dense_ref(q, k, v, valid_length=vl)
    e = _max_err(out, ref)
    if e > 2e-3:
        raise AssertionError(f"kv_valid out_err={e}")
    return {"out_err": e}


def check_flash_t2048():
    """T=2048 blockwise path (the long-context tile) fwd+bwd, bf16."""
    import jax
    import jax.numpy as jnp
    from tpu_mx.kernels.flash_attention import mha_flash_attention
    b, h, t, d = 1, 4, 2048, 64
    key = jax.random.PRNGKey(4)
    qk, kk, vk = jax.random.split(key, 3)
    q = jax.random.normal(qk, (b, h, t, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
    v = jax.random.normal(vk, (b, h, t, d), jnp.bfloat16)
    out = mha_flash_attention(q, k, v, causal=True)
    ref = _dense_ref(q, k, v, causal=True)
    e = _max_err(out, ref)
    g = jax.grad(lambda q, k, v: mha_flash_attention(
        q, k, v, causal=True).astype(jnp.float32).sum(),
        argnums=(0, 1, 2))(q, k, v)
    finite = all(bool(jnp.isfinite(x.astype(jnp.float32)).all()) for x in g)
    if e > 6e-2 or not finite:
        raise AssertionError(f"T=2048: out_err={e} grads_finite={finite}")
    return {"out_err": e, "grads_finite": finite}


@_highest_precision
def check_ring_inner_chunking():
    """Ring attention with O(T/n·C) inner chunking (r3 commit 75dab47,
    never chip-run) at T=2048 on an sp=1 single-chip mesh: the full
    shard_map ring body — scan, ppermute, chunked local attention —
    compiles and matches dense numerics on real silicon."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    import numpy as np
    from tpu_mx.parallel.ring_attention import ring_attention
    b, h, t, d = 1, 4, 2048, 64
    key = jax.random.PRNGKey(5)
    qk, kk, vk = jax.random.split(key, 3)
    q = jax.random.normal(qk, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
    v = jax.random.normal(vk, (b, h, t, d), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    out = ring_attention(q, k, v, mesh, causal=True, step_chunk=512)
    ref = _dense_ref(q, k, v, causal=True)
    e = _max_err(out, ref)
    if e > 2e-3:
        raise AssertionError(f"ring sp=1 T=2048 out_err={e}")
    return {"out_err": e, "step_chunk": 512}


def check_bert_remat_batch512():
    """The full BERT-base remat train step at batch 512 — the exact config
    that OOM'd pre-remat in round 3 (27 GB > 16 GB HBM).  Runs 3 steps and
    records rough seq/s (the bench owns the official number)."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.models.bert import BERTModel, bert_base_config
    from tpu_mx.parallel import CompiledTrainStep
    batch, seq_len = 512, 128
    cfg = bert_base_config(max_len=seq_len)
    net = BERTModel(cfg, dtype="bfloat16", remat=True)
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = rng.randint(4, cfg["vocab_size"], (batch, seq_len)).astype(
        np.int32)
    types = np.zeros((batch, seq_len), np.int32)
    n_masked = max(1, int(0.15 * seq_len))
    positions = np.stack([rng.choice(seq_len, n_masked, replace=False)
                          for _ in range(batch)]).astype(np.int32)
    labels = np.take_along_axis(tokens, positions, axis=1)
    net(nd.array(tokens[:1]), nd.array(types[:1]), None,
        nd.array(positions[:1]))

    class MLMLoss(gluon.loss.Loss):
        def __init__(self, **kw):
            super().__init__(weight=None, batch_axis=0, **kw)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, logits, labels):
            vocab = logits.shape[-1]
            return F.mean(self._ce(F.reshape(logits, shape=(-1, vocab)),
                                   F.reshape(labels, shape=(-1,))))

    opt = mx.optimizer.create("lamb", learning_rate=1e-4,
                              multi_precision=True)
    step = CompiledTrainStep(net, MLMLoss(), opt)
    args = (nd.array(tokens), nd.array(types), None, nd.array(positions),
            nd.array(labels))
    fetch = lambda l: float(np.asarray(l._data).ravel()[0])
    loss = step.step(*args)
    first = fetch(loss)
    t0 = time.perf_counter()
    n = 3
    for _ in range(n):
        loss = step.step(*args)
    last = fetch(loss)
    dt = time.perf_counter() - t0
    if not np.isfinite(first) or not np.isfinite(last):
        raise AssertionError(f"non-finite loss: first={first} last={last}")
    return {"batch": batch, "seq_len": seq_len, "remat": True,
            "rough_seqs_per_sec": round(batch * n / dt, 1),
            "loss_first": first, "loss_last": last}


def check_async_checkpoint():
    """Async sharded checkpoint on silicon (the one r4 drive the tunnel
    wedge interrupted — CPU-tested, chip-unvalidated until now): save with
    block=False while training keeps stepping (donated buffers are
    overwritten under the in-flight save), then restore into a FRESH step
    and verify the resumed trajectory is numerically identical to the
    original — proof the async machinery snapshotted device state at save
    time, not whatever the buffers held when tensorstore committed."""
    import shutil
    import tempfile
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon import nn
    from tpu_mx.parallel import CompiledTrainStep

    def build():
        mx.random.seed(42)
        # explicit prefixes: both build() calls must produce identical
        # parameter names (the auto-name counter is process-global)
        net = nn.HybridSequential(prefix="ckptnet_")
        net.add(nn.Dense(256, activation="relu", prefix="fc1_"),
                nn.Dense(10, prefix="fc2_"))
        net.initialize(init="xavier")
        net(nd.zeros((2, 64)))
        opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9)
        return CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 opt)

    rng = np.random.RandomState(0)
    xs = [nd.array(rng.rand(32, 64).astype(np.float32)) for _ in range(5)]
    ys = [nd.array(rng.randint(0, 10, (32,)).astype(np.float32))
          for _ in range(5)]
    fetch = lambda l: float(np.asarray(l._data).ravel()[0])

    path = tempfile.mkdtemp(prefix="tmx_ckpt_")
    ckpt_dir = os.path.join(path, "step")
    try:
        a = build()
        for i in range(2):
            a.step(xs[i], ys[i])
        a.save_checkpoint(ckpt_dir, block=False)
        # keep training THROUGH the in-flight save: with donate=True these
        # steps overwrite the very buffers being checkpointed
        ref_losses = [fetch(a.step(xs[i], ys[i])) for i in range(2, 5)]
        a.wait_for_checkpoint()

        b = build()
        b.load_checkpoint(ckpt_dir)
        res_losses = [fetch(b.step(xs[i], ys[i])) for i in range(2, 5)]
        err = max(abs(r - s) for r, s in zip(ref_losses, res_losses))
        if err != 0.0:
            raise AssertionError(
                f"resumed trajectory diverged: ref={ref_losses} "
                f"restored={res_losses} max_abs_err={err}")
        return {"ref_losses": ref_losses, "restored_losses": res_losses,
                "bitwise_identical": True}
    finally:
        shutil.rmtree(path, ignore_errors=True)


def check_quantized_inference_jit():
    """INT8 inference through the wrapper's own jax.jit on silicon (the
    r4 16→146 img/s fix): a quantized conv+dense net must match its
    float reference within int8 tolerance AND run as ONE compiled
    program (the jit cache populates), not per-op eager dispatch."""
    import numpy as np
    from tpu_mx import gluon, nd
    from tpu_mx.contrib import quantization as q
    from tpu_mx.gluon import nn

    rng = np.random.RandomState(0)
    net = nn.HybridSequential(prefix="qchipnet_")
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu",
                      prefix="c1_"),
            nn.MaxPool2D(pool_size=2),
            nn.Conv2D(16, kernel_size=3, padding=1, activation="relu",
                      prefix="c2_"),
            nn.Dense(32, activation="relu", prefix="d1_"),
            nn.Dense(4, prefix="d2_"))
    net.initialize(init="xavier")
    calib = nd.array(rng.rand(16, 1, 12, 12).astype(np.float32))
    net(calib)
    qnet = q.quantize_net(net, calib_data=calib)
    x = nd.array(rng.rand(8, 1, 12, 12).astype(np.float32))
    ref = net(x).asnumpy()
    out = qnet(x).asnumpy()
    if qnet._jit is None:
        raise AssertionError("quantized net did not take the jit path "
                             "(TPUMX_QUANT_JIT unset should default on)")
    scale = float(np.abs(ref).max()) + 1e-8
    rel = float(np.abs(out - ref).max()) / scale
    if rel > 0.12:
        raise AssertionError(f"int8 divergence {rel:.4f} > 0.12")
    return {"rel_err": rel, "jit_path": True}


def check_device_prefetch_feed():
    """The TPU-grade input feed on silicon: uint8/NHWC batches through
    DevicePrefetchIter(normalize=) must arrive on device as bf16 with
    (x-mean)/std applied in f32 BEFORE the cast, and feed a train step."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, io, nd
    from tpu_mx.gluon import nn
    from tpu_mx.parallel import CompiledTrainStep

    rng = np.random.RandomState(1)
    n, h, w, c = 32, 8, 8, 3
    data = rng.randint(0, 256, (n, h, w, c)).astype(np.uint8)
    labels = rng.randint(0, 4, (n,)).astype(np.float32)
    mean, std = 127.0, 64.0
    base = io.NDArrayIter(data, labels, batch_size=8)
    it = io.DevicePrefetchIter(base, cast_data="bfloat16",
                               normalize=(mean, std))
    batch = next(iter(it))
    xb = batch.data[0]
    if str(xb.dtype) != "bfloat16":
        raise AssertionError(f"feed dtype {xb.dtype}, want bfloat16")
    want = ((data[:8].astype(np.float32) - mean) / std)
    got = xb.asnumpy().astype(np.float32)
    err = float(np.abs(got - want).max())
    if err > 0.02:  # bf16 quantization of a ~[-2, 2] range
        raise AssertionError(f"normalize-before-cast violated: err={err}")

    net = nn.HybridSequential(prefix="feednet_")
    net.add(nn.Dense(16, activation="relu", prefix="f1_"),
            nn.Dense(4, prefix="f2_"))
    net.initialize(init="xavier")
    net(nd.zeros((2, h * w * c)))
    step = CompiledTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.create("sgd", learning_rate=0.1))
    flat = nd.reshape(xb, shape=(8, -1))
    loss = step.step(flat, batch.label[0])
    lval = float(loss.asnumpy().ravel()[0])
    if not np.isfinite(lval):
        raise AssertionError(f"non-finite loss {lval}")
    return {"feed_dtype": "bfloat16", "normalize_err": err,
            "step_loss": lval}


def _consistency_compute(out_path):
    """Shared body of the cross-backend oracle: eager conv+relu+pool+dense
    forward/backward on WHATEVER backend this process has, saved to npz.
    Ends in logits (NOT softmax — sum-of-softmax is constant 1, which
    would zero every gradient and make the comparison vacuous)."""
    import numpy as np
    from tpu_mx import autograd, nd

    rng = np.random.RandomState(3)
    x = rng.rand(4, 6, 6, 3).astype(np.float32)
    w = (rng.rand(8, 3, 3, 3).astype(np.float32) - 0.5) * 0.5
    dw = (rng.rand(10, 8).astype(np.float32) - 0.5) * 0.5
    nds = [nd.array(a) for a in (x, w, dw)]
    for a in nds:
        a.attach_grad()
    with autograd.record():
        xx, ww, dd = nds
        y = nd.Convolution(xx, ww, num_filter=8, kernel=(3, 3),
                           pad=(1, 1), no_bias=True, layout="NHWC")
        y = nd.Activation(y, act_type="relu")
        y = nd.Pooling(y, kernel=(6, 6), pool_type="avg", global_pool=True,
                       layout="NHWC")
        logits = nd.FullyConnected(nd.flatten(y), dd, None, no_bias=True,
                                   num_hidden=10)
        # non-constant scalar: quadratic in the logits, grads exercise
        # every input's backward
        loss = (logits * logits).sum()
    loss.backward()
    np.savez(out_path, out=logits.asnumpy(),
             **{f"g{i}": a.grad.asnumpy() for i, a in enumerate(nds)})


@_highest_precision
def check_cpu_tpu_consistency():
    """SURVEY §4's check_consistency oracle on silicon: the same eager
    conv+relu+pool+dense forward/backward on XLA:CPU and the real chip
    must agree (the reference's [cpu, gpu] cross-backend check, TPU
    edition).  The CPU leg runs in a SUBPROCESS with JAX_PLATFORMS=cpu —
    this process is pinned to the axon platform at interpreter startup,
    so in-process context.cpu(0) would silently fall back to the TPU
    device and compare the chip against itself."""
    import os
    import subprocess
    import sys
    import tempfile
    import numpy as np

    import jax
    if jax.devices()[0].platform != "tpu":
        raise AssertionError("not on a TPU backend")

    with tempfile.TemporaryDirectory(prefix="tmx_consist_") as td:
        cpu_npz = os.path.join(td, "cpu.npz")
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""   # sitecustomize skips axon
        env["JAX_PLATFORMS"] = "cpu"
        # no trailing empty entry: "REPO:" would make Python treat the
        # CWD as a path entry and risk module shadowing
        extra = env.get("PYTHONPATH")
        env["PYTHONPATH"] = REPO + (os.pathsep + extra if extra else "")
        script = (
            "import sys; sys.path.insert(0, %r); "
            "import tpu_validate; "
            "import jax; assert jax.devices()[0].platform == 'cpu'; "
            "tpu_validate._consistency_compute(%r)"
            % (os.path.dirname(os.path.abspath(__file__)), cpu_npz))
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            # the child's stderr is the only diagnostic there is — fold
            # its tail into the artifact instead of a bare exit status
            raise AssertionError(
                "cpu reference subprocess failed (rc=%d): %s"
                % (proc.returncode, (proc.stderr or "")[-800:]))
        ref = np.load(cpu_npz)

        tpu_npz = os.path.join(td, "tpu.npz")
        _consistency_compute(tpu_npz)      # this process: the real chip
        got = np.load(tpu_npz)

        errs = {}
        for key in ref.files:
            scale = float(np.abs(ref[key]).max()) + 1e-8
            rel = float(np.abs(got[key] - ref[key]).max()) / scale
            errs[key] = rel
            if rel > 2e-3:
                raise AssertionError(
                    f"cpu-vs-tpu mismatch on {key}: rel={rel:.5f}")
    return {"ctxs": ["cpu (subprocess)", "tpu"], "rel_errs": errs}


CHECKS = [
    ("flash_fwd_bwd_vs_dense", check_flash_fwd_bwd_vs_dense),
    ("flash_bias_layouts", check_flash_bias_layouts),
    ("flash_dropout_inkernel", check_flash_dropout),
    ("flash_kv_valid", check_flash_kv_valid),
    ("flash_t2048", check_flash_t2048),
    ("ring_inner_chunking_t2048", check_ring_inner_chunking),
    ("bert_remat_batch512", check_bert_remat_batch512),
    ("async_checkpoint_under_training", check_async_checkpoint),
    ("quantized_inference_jit", check_quantized_inference_jit),
    ("device_prefetch_feed", check_device_prefetch_feed),
    ("cpu_tpu_consistency", check_cpu_tpu_consistency),
]


def main():
    ap = argparse.ArgumentParser()
    from artifact_protocol import artifact
    ap.add_argument("--out", default=artifact("TPU_VALIDATION"))
    ap.add_argument("--skip-bert", action="store_true")
    ap.add_argument("--only", default=None,
                    help="comma-separated check names")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (env vars are too late "
                         "under the environment's sitecustomize, which "
                         "pins JAX_PLATFORMS=axon at interpreter startup; "
                         "mirror tests/conftest.py and override via "
                         "jax.config)")
    args = ap.parse_args()
    only = set(args.only.split(",")) if args.only else None
    if only:
        unknown = only - {name for name, _ in CHECKS}
        if unknown:
            log(f"unknown --only check(s): {sorted(unknown)}; "
                f"valid: {[n for n, _ in CHECKS]}")
            return 2

    global jax
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        # share the bench's persistent compile cache: retries after a
        # mid-sweep wedge skip straight to execution
        from tpu_mx.runtime import enable_shared_compilation_cache
        enable_shared_compilation_cache()
    from artifact_protocol import load_prior, refuses_clobber, write_atomic
    devs = jax.devices()
    platform = devs[0].platform
    record = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
              "platform": platform, "n_devices": len(devs), "checks": {}}
    prior = load_prior(args.out)
    if refuses_clobber(prior, platform):
        log(f"platform is {platform}, not tpu; refusing to overwrite the "
            f"hardware artifact {args.out} (pass --out elsewhere)")
        return 1
    ran = set()
    if platform != "tpu":
        record["skipped"] = True
        record["reason"] = f"platform is {platform}, not tpu"
        log(f"not a TPU backend ({platform}); writing skip record")
    else:
        record["skipped"] = False
        # seed with the prior artifact's passing rows for checks still in
        # the suite: a mid-sweep wedge (the recurring failure mode) must
        # not cost previously-recorded green results — each seeded row is
        # REPLACED the moment its check re-executes below, so a full
        # sweep still re-proves everything it reaches
        if prior.get("platform") == "tpu":
            current = {name for name, _ in CHECKS}
            for name, row in (prior.get("checks") or {}).items():
                if name in current and isinstance(row, dict) and \
                        row.get("ok") is True:
                    seeded = dict(row)
                    # setdefault: across two consecutive wedged runs the
                    # chain must keep pointing at the run that actually
                    # MEASURED the check, not the intermediate carrier
                    seeded.setdefault("carried_from", prior.get("ts"))
                    record["checks"][name] = seeded
        for name, fn in CHECKS:
            if only and name not in only:
                continue
            if args.skip_bert and name == "bert_remat_batch512":
                # don't clobber a carried green row with {ok: None} — that
                # would drop the measured pass (and its carried_from chain)
                # from every later wedge-seeded run
                record["checks"].setdefault(
                    name, {"ok": None, "skipped": True})
                continue
            ran.add(name)
            log(f"running {name}...")
            t0 = time.perf_counter()
            try:
                detail = fn()
                record["checks"][name] = {
                    "ok": True, "seconds": round(time.perf_counter() - t0, 1),
                    "detail": detail}
                log(f"  {name}: OK ({record['checks'][name]['seconds']}s)")
            except Exception as e:
                record["checks"][name] = {
                    "ok": False, "seconds": round(time.perf_counter() - t0, 1),
                    "error": f"{type(e).__name__}: {e}"[:500],
                    "traceback": traceback.format_exc()[-1500:]}
                log(f"  {name}: FAIL {type(e).__name__}: {e}")
            # persist after every check — a later hang must not lose
            # earlier results (the bench lastgood lesson)
            write_atomic(args.out, record)
    if not record.get("skipped"):
        record["ran_this_run"] = sorted(ran)
    write_atomic(args.out, record)
    # rc contract: 0 iff (a) every check EXECUTED this run passed and
    # (b) the merged artifact covers the full current suite all-green —
    # so a wedge-shortened or --only run can't report a green sweep while
    # most checks were neither run nor carried (advisor r4 finding #4)
    current = {name for name, _ in CHECKS}
    ok_run = not record.get("skipped", True) and all(
        record["checks"][n].get("ok") is True
        for n in ran if n in record["checks"])
    # a --skip-bert {ok: None} row is NOT complete: it was neither run
    # nor carried, and rc 0 would report a green sweep over an
    # unmeasured check
    complete = all(
        n in record["checks"] and
        record["checks"][n].get("ok") is True for n in current)
    log(f"done: {args.out} (ran={len(ran) if not record.get('skipped') else 0}"
        f" ok_run={ok_run} merged_complete={complete})")
    return 0 if (ok_run and complete) else 1


if __name__ == "__main__":
    sys.exit(main())
