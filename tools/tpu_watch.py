"""TPU tunnel watcher (VERDICT r3 ask#1: "probe the TPU; the moment it is
up, run the full bench BEFORE building anything new — the tunnel has now
eaten two round-ends").

Runs forever in a side terminal.  Imports NO jax itself (a wedged backend
hangs the importing process inside a C call); every probe is a subprocess
with a hard timeout.  On the first successful probe it runs, in order:

  1. tools/tpu_validate.py   — the real-chip kernel validation sweep
                               (r3's never-chip-run Pallas tail), artifact
                               TPU_VALIDATION_<round>.json
  2. python bench.py         — all four workload benches (resnet50, bert,
                               lstm, ssd — ~13+ min cold-cache); its inner
                               persists BENCH_LASTGOOD.json per sub-bench,
                               so even a mid-run wedge keeps the number;
                               final line lands in BENCH_WATCH_<round>.json

Both keep re-trying on later probes until they have succeeded once (the
tunnel can die mid-run).  Probe results are appended to
TPU_PROBE_LOG_<round>.jsonl and a human-pollable summary is kept in
TPU_WATCH_STATUS.json.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# tools-local imports (mfu_probe, tpu_validate, artifact_protocol) must
# resolve regardless of the entry point — script-dir auto-prepend only
# covers direct `python tools/tpu_watch.py` (advisor r4 finding #3)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from artifact_protocol import artifact  # noqa: E402

LOGDIR = os.path.join(REPO, "watch_logs")
PROBE_LOG = artifact("TPU_PROBE_LOG", ext="jsonl")
STATUS = os.path.join(REPO, "TPU_WATCH_STATUS.json")
VALIDATION = artifact("TPU_VALIDATION")
BENCH_OUT = artifact("BENCH_WATCH")
MFU_OUT = artifact("MFU_PROBE")

PROBE_TIMEOUT = 120
PROBE_INTERVAL_DOWN = 180      # probe cadence while the tunnel is down
PROBE_INTERVAL_DONE = 1800     # cadence once all work has succeeded
FAIL_BACKOFF = 300             # wait after a failed validate/bench attempt

PROBE_SNIPPET = ("import jax, json; ds = jax.devices(); "
                 "print(json.dumps({'platform': ds[0].platform, "
                 "'n': len(ds)}))")


def log(msg):
    print(f"[watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def ts():
    return time.strftime("%Y-%m-%dT%H:%M:%S")


def probe():
    """One backend probe in a subprocess.  Returns (up, detail)."""
    try:
        out = subprocess.run([sys.executable, "-c", PROBE_SNIPPET],
                             capture_output=True, text=True,
                             timeout=PROBE_TIMEOUT, cwd=REPO)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {PROBE_TIMEOUT}s"
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    if out.returncode == 0 and lines:
        info = json.loads(lines[-1])
        return info.get("platform") == "tpu", info
    return False, f"rc={out.returncode} stderr={out.stderr[-200:]}"


def run_logged(tag, cmd, timeout, env=None):
    """Run cmd with stdout+stderr teed to a log file; returns (rc, stdout)
    or (None, reason) on timeout."""
    os.makedirs(LOGDIR, exist_ok=True)
    path = os.path.join(LOGDIR, f"{tag}_{time.strftime('%H%M%S')}.log")
    log(f"running {tag}: {' '.join(cmd)} (timeout {timeout}s, log {path})")
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    with open(path, "w") as f:
        try:
            out = subprocess.run(cmd, stdout=subprocess.PIPE,
                                 stderr=f, text=True, timeout=timeout,
                                 cwd=REPO, env=full_env)
        except subprocess.TimeoutExpired:
            return None, f"{tag} timed out after {timeout}s (log: {path})"
    with open(path, "a") as f:
        f.write(f"\n--- stdout ---\n{out.stdout}")
    return out.returncode, out.stdout


def validation_done():
    """Done = ran on a real TPU, every check in the CURRENT suite has a
    record, and every executed check passed.  Requiring every current
    check name keeps this drift-proof the way MFU_EXPECTED is: a check
    added after the artifact was recorded makes the watcher re-run the
    sweep instead of calling stale coverage done.  An all-fail (or
    partial-fail) artifact keeps the watcher retrying on later probes —
    the docstring contract is 'until they have SUCCEEDED once'."""
    from tpu_validate import CHECKS  # stdlib-only module top, like mfu_probe
    try:
        with open(VALIDATION) as f:
            rec = json.load(f)
        checks = rec.get("checks") or {}
        # ok must be literally True: a --skip-bert {ok: None} row is an
        # unmeasured check and must keep the watcher re-running the sweep
        return rec.get("skipped") is False and checks and \
            all(name in checks for name, _ in CHECKS) and \
            all(c.get("ok") is True for c in checks.values())
    except (OSError, ValueError, AttributeError):
        return False


def bench_done():
    try:
        with open(BENCH_OUT) as f:
            rec = json.load(f)
        return rec.get("value", 0) > 0 and not rec.get("stale")
    except (OSError, ValueError):
        return False


# imported from the probe itself so the done-predicate can never drift
# from what the probe actually produces (a hand-maintained copy once
# listed a key the probe never emitted — mfu_done() stayed false and the
# watcher re-ran the 90-minute probe every backoff cycle)
from mfu_probe import DEFAULT_CONFIGS as MFU_EXPECTED  # noqa: E402
from artifact_protocol import write_atomic  # noqa: E402  (see sys.path
# insert at the top; artifact() is imported there for the path constants)


def mfu_done():
    """Done = the probe RAN TO COMPLETION (every expected config has a
    record — success or a legitimate per-config failure like OOM) with at
    least one success.  A mid-run wedge leaves configs missing, so the
    watcher keeps retrying; a completed run with one OOM rung does not
    retry forever."""
    try:
        with open(MFU_OUT) as f:
            rec = json.load(f)
        configs = rec.get("configs", {})
        return rec.get("skipped") is False and \
            all(k in configs for k in MFU_EXPECTED) and \
            any("error" not in c for c in configs.values())
    except (OSError, ValueError, AttributeError):
        return False


def write_status(**kw):
    kw["ts"] = ts()
    write_atomic(STATUS, kw)


def main():
    n_probe = up_count = 0
    last_fail = 0.0
    log(f"watching for the TPU backend (probe every "
        f"{PROBE_INTERVAL_DOWN}s while down)")
    while True:
        n_probe += 1
        up, detail = probe()
        with open(PROBE_LOG, "a") as f:
            f.write(json.dumps({"ts": ts(), "up": up,
                                "detail": detail}) + "\n")
        if up:
            up_count += 1
        v_done, b_done, m_done = validation_done(), bench_done(), mfu_done()
        write_status(up=up, probes=n_probe, up_probes=up_count,
                     validation_done=bool(v_done), bench_done=bool(b_done),
                     mfu_done=bool(m_done), detail=detail)
        if up and not (v_done and b_done and m_done) and \
                time.time() - last_fail > FAIL_BACKOFF:
            log(f"TPU is UP ({detail}); validation_done={bool(v_done)} "
                f"bench_done={bool(b_done)}")
            ok = True
            # bench FIRST (VERDICT r3 ask#1: capture the round's numbers
            # before anything else — the tunnel can die again mid-sweep)
            if not b_done:
                rc, out = run_logged("bench", [sys.executable, "bench.py"],
                                     5400)
                log(f"bench rc={rc}")
                lines = [ln for ln in (out or "").splitlines()
                         if ln.startswith("{")]
                if rc == 0 and lines:
                    rec = json.loads(lines[-1])
                    write_atomic(BENCH_OUT, rec)
                    log(f"bench record: value={rec.get('value')} "
                        f"stale={rec.get('stale', False)}")
                    ok = ok and rec.get("value", 0) > 0 and \
                        not rec.get("stale")
                else:
                    ok = False
            if not v_done:
                rc, out = run_logged(
                    "validate",
                    [sys.executable, "tools/tpu_validate.py"], 5400)
                log(f"validate rc={rc}")
                # artifact written per-check by the tool; rc None means
                # timeout/wedge, rc 1 means some check failed — both
                # leave validation_done() false and retry next cycle
                ok = ok and rc == 0
            if not mfu_done():
                rc, out = run_logged(
                    "mfu", [sys.executable, "tools/mfu_probe.py"], 5400)
                log(f"mfu probe rc={rc}")
                ok = ok and rc == 0
            if not ok:
                last_fail = time.time()
            write_status(up=up, probes=n_probe, up_probes=up_count,
                         validation_done=bool(validation_done()),
                         bench_done=bool(bench_done()),
                         mfu_done=bool(mfu_done()), detail=detail)
        done = validation_done() and bench_done() and mfu_done()
        time.sleep(PROBE_INTERVAL_DONE if done else PROBE_INTERVAL_DOWN)


if __name__ == "__main__":
    main()
