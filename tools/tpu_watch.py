"""TPU tunnel watcher (VERDICT r3 ask#1: "probe the TPU; the moment it is
up, run the full bench BEFORE building anything new — the tunnel has now
eaten two round-ends").

Runs forever in a side terminal.  Imports NO jax itself (a wedged backend
hangs the importing process inside a C call); every probe is a subprocess
with a hard timeout.  On the first successful probe it runs, in order:

  1. tools/tpu_validate.py   — the real-chip kernel validation sweep
                               (r3's never-chip-run Pallas tail), artifact
                               TPU_VALIDATION_<round>.json
  2. python bench.py         — all four workload benches (resnet50, bert,
                               lstm, ssd — ~13+ min cold-cache); its inner
                               persists BENCH_LASTGOOD.json per sub-bench,
                               so even a mid-run wedge keeps the number;
                               final line lands in BENCH_WATCH_<round>.json

Both keep re-trying on later probes until they have succeeded once (the
tunnel can die mid-run).  Probe results are appended to
TPU_PROBE_LOG_<round>.jsonl and a human-pollable summary is kept in
TPU_WATCH_STATUS.json.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# tools-local imports (mfu_probe, tpu_validate, artifact_protocol) must
# resolve regardless of the entry point — script-dir auto-prepend only
# covers direct `python tools/tpu_watch.py` (advisor r4 finding #3)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from artifact_protocol import artifact  # noqa: E402

LOGDIR = os.path.join(REPO, "watch_logs")
PROBE_LOG = artifact("TPU_PROBE_LOG", ext="jsonl")
STATUS = os.path.join(REPO, "TPU_WATCH_STATUS.json")
VALIDATION = artifact("TPU_VALIDATION")
BENCH_OUT = artifact("BENCH_WATCH")
BENCH_QUICK_OUT = artifact("BENCH_QUICK")
MFU_OUT = artifact("MFU_PROBE")

PROBE_TIMEOUT = 120
PROBE_INTERVAL_DOWN = 180      # probe cadence while the tunnel is down
PROBE_INTERVAL_DONE = 1800     # cadence once all work has succeeded
FAIL_BACKOFF = 300             # wait after a failed validate/bench attempt

PROBE_SNIPPET = ("import jax, json; ds = jax.devices(); "
                 "print(json.dumps({'platform': ds[0].platform, "
                 "'n': len(ds)}))")


def log(msg):
    print(f"[watch {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def ts():
    return time.strftime("%Y-%m-%dT%H:%M:%S")


def probe():
    """One backend probe in a subprocess, under the chip lock (an
    unlocked probe IS a second jax process — the exact wedge the lock
    exists to prevent).  Returns (up, detail); raises ChipBusy when
    another process owns the chip."""
    lock = _chip_lock()
    try:
        try:
            out = subprocess.run([sys.executable, "-c", PROBE_SNIPPET],
                                 capture_output=True, text=True,
                                 timeout=PROBE_TIMEOUT, cwd=REPO)
        except subprocess.TimeoutExpired:
            return False, f"probe timed out after {PROBE_TIMEOUT}s"
    finally:
        lock.close()
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    if out.returncode == 0 and lines:
        info = json.loads(lines[-1])
        return info.get("platform") == "tpu", info
    return False, f"rc={out.returncode} stderr={out.stderr[-200:]}"


CHIP_LOCK = os.path.join(REPO, ".chip_lock")


class ChipBusy(Exception):
    """Another process (the round-end driver bench) holds the chip."""


def _chip_lock():
    """Non-blocking flock on the shared single-chip lock.  bench.py's
    outer takes the same lock (blocking) so the round-end driver bench
    and a watcher stage can never hit the chip concurrently — two jax
    processes wedge each other in make_c_api_client and both lose.
    flock self-releases on process death: no stale-lock handling."""
    import fcntl
    f = open(CHIP_LOCK, "w")
    try:
        fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        f.close()
        raise ChipBusy
    return f


def run_logged(tag, cmd, timeout, env=None):
    """Run cmd with stdout+stderr teed to a log file, holding the chip
    lock; returns (rc, stdout) or (None, reason) on timeout/chip-busy."""
    os.makedirs(LOGDIR, exist_ok=True)
    path = os.path.join(LOGDIR, f"{tag}_{time.strftime('%H%M%S')}.log")
    lock = _chip_lock()  # ChipBusy propagates: the caller yields the window
    log(f"running {tag}: {' '.join(cmd)} (timeout {timeout}s, log {path})")
    full_env = dict(os.environ)
    full_env["TPUMX_CHIP_LOCK_HELD"] = "1"  # children skip re-acquiring
    if env:
        full_env.update(env)
    import signal
    try:
        with open(path, "w") as f:
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=f,
                                    text=True, cwd=REPO, env=full_env,
                                    start_new_session=True)
            try:
                stdout, _ = proc.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                # kill the WHOLE group: subprocess kill alone leaves e.g.
                # bench.py's --inner jax grandchild alive on the chip
                # while the released lock tells the driver it is free
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait()
                return None, f"{tag} timed out after {timeout}s (log: {path})"
        with open(path, "a") as f:
            f.write(f"\n--- stdout ---\n{stdout}")
        return proc.returncode, stdout
    finally:
        lock.close()


def validation_done():
    """Done = ran on a real TPU, every check in the CURRENT suite has a
    record, and every executed check passed.  Requiring every current
    check name keeps this drift-proof the way MFU_EXPECTED is: a check
    added after the artifact was recorded makes the watcher re-run the
    sweep instead of calling stale coverage done.  An all-fail (or
    partial-fail) artifact keeps the watcher retrying on later probes —
    the docstring contract is 'until they have SUCCEEDED once'."""
    from tpu_validate import CHECKS  # stdlib-only module top, like mfu_probe
    try:
        with open(VALIDATION) as f:
            rec = json.load(f)
        checks = rec.get("checks") or {}
        # ok must be literally True: a --skip-bert {ok: None} row is an
        # unmeasured check and must keep the watcher re-running the sweep
        return rec.get("skipped") is False and checks and \
            all(name in checks for name, _ in CHECKS) and \
            all(c.get("ok") is True for c in checks.values())
    except (OSError, ValueError, AttributeError):
        return False


def _bench_record_done(path):
    try:
        with open(path) as f:
            rec = json.load(f)
        return rec.get("value", 0) > 0 and not rec.get("stale")
    except (OSError, ValueError):
        return False


def bench_done():
    return _bench_record_done(BENCH_OUT)


def bench_quick_done():
    # done once EITHER bench has a fresh record: after the full bench
    # succeeds, a re-run of the quick stage would persist a fresher
    # 5-iter number over the official 30-iter record (freshest-wins)
    return _bench_record_done(BENCH_QUICK_OUT) or bench_done()


# imported from the probe itself so the done-predicate can never drift
# from what the probe actually produces (a hand-maintained copy once
# listed a key the probe never emitted — mfu_done() stayed false and the
# watcher re-ran the 90-minute probe every backoff cycle)
from mfu_probe import DEFAULT_CONFIGS as MFU_EXPECTED  # noqa: E402
from artifact_protocol import write_atomic  # noqa: E402  (see sys.path
# insert at the top; artifact() is imported there for the path constants)


def mfu_done():
    """Done = the probe RAN TO COMPLETION (every expected config has a
    record — success or a legitimate per-config failure like OOM) with at
    least one success.  A mid-run wedge leaves configs missing, so the
    watcher keeps retrying; a completed run with one OOM rung does not
    retry forever."""
    try:
        with open(MFU_OUT) as f:
            rec = json.load(f)
        configs = rec.get("configs", {})
        return rec.get("skipped") is False and \
            all(k in configs for k in MFU_EXPECTED) and \
            any("error" not in c for c in configs.values())
    except (OSError, ValueError, AttributeError):
        return False


def write_status(**kw):
    kw["ts"] = ts()
    write_atomic(STATUS, kw)


# every coverage expectation is IMPORTED from the tool that produces the
# artifact (the MFU_EXPECTED pattern above: a hand-maintained copy once
# kept mfu_done() false forever and re-ran the 90-minute probe every
# backoff cycle); all three modules keep stdlib-only tops
from flash_sweep import DEFAULT_LENS as FLASH_LENS          # noqa: E402
from int8_ab import ARMS as INT8_ARMS                       # noqa: E402
from longctx_bench import (DEFAULT_DENSE_AT as LC_DENSE_AT,  # noqa: E402
                           DEFAULT_LENS as LC_LENS)
from artifact_protocol import load_prior                    # noqa: E402


def _profile_done(path):
    rec = load_prior(path)
    return rec.get("platform") == "tpu" and \
        bool(rec.get("families_us_per_step"))


def bn_ab_done():
    leg = load_prior(artifact("BN_AB")).get("legacy_onepass0") or {}
    return leg.get("value", 0) > 0 and not leg.get("stale")


def resweep384_done():
    rec = load_prior(artifact("RESNET_B384")).get("batch384") or {}
    return rec.get("value", 0) > 0 and not rec.get("stale")


def int8_ab_done():
    arms = load_prior(artifact("INT8_AB")).get("arms") or {}
    return all(a in arms and ("img_per_s" in arms[a] or "error" in arms[a])
               for a in INT8_ARMS) and \
        any("img_per_s" in arms.get(a, {}) for a in INT8_ARMS)


def flash_sweep_done():
    # per-T "complete" is stamped by the tool only after every block
    # combo + the best/ratio summary: a wedge mid-row re-runs the stage
    # (the artifact merge keeps the finished combos)
    sweep = load_prior(artifact("FLASH_SWEEP")).get("sweep") or {}
    return all(sweep.get(f"T={t}", {}).get("complete") for t in FLASH_LENS)


def longctx_done():
    rec = load_prior(artifact("LONGCTX"))
    rows = rec.get("flash_kernel") or {}
    dense = rec.get("dense_comparison") or {}
    return all(f"T={t}" in rows for t in LC_LENS) and \
        any("tok_per_s" in rows.get(f"T={t}", {}) for t in LC_LENS) and \
        f"T={LC_DENSE_AT}" in dense


def _run_bench(tag, extra_env=None):
    """One bench.py run; returns (ok, record_or_None)."""
    rc, out = run_logged(tag, [sys.executable, "bench.py"], 5400,
                         env=extra_env)
    lines = [ln for ln in (out or "").splitlines() if ln.startswith("{")]
    if rc == 0 and lines:
        rec = json.loads(lines[-1])
        return rec.get("value", 0) > 0 and not rec.get("stale"), rec
    return False, None


def stage_bench_quick():
    """Resnet-only, 5 timing iters, one attempt: banks a fresh PRIMARY
    metric number in ~3-5 min (one compile + 15 steps).  Today's window
    lasted ~1 minute and the full 5-leg bench needs ~30 — a marginal
    window must still produce an official-store record.  Persists to the
    OFFICIAL lastgood (the full bench overwrites it with the 30-iter
    number when it completes), and its resnet compile warms the
    persistent .jax_cache for the full run."""
    # 15 iters (was 5): the r5 quick-vs-full spread was 8.5% from
    # iteration count alone (VERDICT r5 weak#2); 10 extra timed steps
    # cost ~seconds against the leg's one compile.  bench.py additionally
    # strips vs_baseline from any resnet record under 30 iters, so the
    # quick number can never read as a baseline regression.
    ok, rec = _run_bench("bench_quick", {
        "BENCH_MODELS": "resnet50", "BENCH_ITERS": "15",
        "BENCH_ATTEMPTS": "1", "BENCH_TIMEOUT": "900"})
    if rec is not None:
        write_atomic(BENCH_QUICK_OUT, rec)
        log(f"bench_quick record: value={rec.get('value')} "
            f"stale={rec.get('stale', False)}")
    return ok


def stage_bench():
    # skip-fresh: a retry after a mid-run wedge carries legs measured in
    # the last 4h (their own measured_at rides along) and spends the
    # window on the missing ones; the quick stage's 5-iter resnet never
    # qualifies (bench.py's min-iters gate), and the A/B stages use their
    # own lastgood paths so they are unaffected
    ok, rec = _run_bench("bench", {"BENCH_SKIP_FRESH": "14400"})
    if rec is not None:
        write_atomic(BENCH_OUT, rec)
        log(f"bench record: value={rec.get('value')} "
            f"stale={rec.get('stale', False)}")
    return ok


def stage_validate():
    rc, _ = run_logged("validate",
                       [sys.executable, "tools/tpu_validate.py"], 5400)
    # artifact written per-check by the tool; rc None = timeout/wedge,
    # rc 1 = a check failed — both keep the stage pending for retry
    return rc == 0


def stage_profile_bert():
    rc, _ = run_logged("profile_bert", [
        sys.executable, "tools/chip_profile.py", "--model", "bert",
        "--batch", "384"], 2400)
    return rc == 0


def stage_profile_resnet():
    rc, _ = run_logged("profile_resnet", [
        sys.executable, "tools/chip_profile.py", "--model", "resnet",
        "--batch", "256"], 2400)
    return rc == 0


def stage_bn_ab():
    """Legacy two-pass-BN arm of the r5 byte-diet A/B (the official bench
    runs the one-pass default).  Its OWN lastgood path: the A/B arm must
    never pollute the official store."""
    ok, rec = _run_bench("bn_ab", {
        "TPUMX_BN_ONEPASS": "0", "BENCH_MODELS": "resnet50",
        "BENCH_ATTEMPTS": "1",
        "BENCH_LASTGOOD_PATH": os.path.join(LOGDIR, "bn_ab_lastgood.json")})
    if ok and rec:
        write_atomic(artifact("BN_AB"), {
            "ts": ts(), "legacy_onepass0": rec,
            "note": "TPUMX_BN_ONEPASS=0 arm; compare the official bench "
                    "resnet record (one-pass default) against this"})
        log(f"bn_ab legacy arm: {rec.get('value')}")
    return ok


def stage_resweep384():
    """ResNet batch re-sweep at the post-BN-diet byte budget (ROUND5
    plan item 7): fewer bytes/step can move the 256 optimum."""
    ok, rec = _run_bench("resweep384", {
        "BENCH_MODELS": "resnet50", "BENCH_BATCH": "384",
        "BENCH_ATTEMPTS": "1",
        "BENCH_LASTGOOD_PATH": os.path.join(LOGDIR,
                                            "resweep384_lastgood.json")})
    if ok and rec:
        write_atomic(artifact("RESNET_B384"), {
            "ts": ts(), "batch384": rec,
            "note": "BENCH_BATCH=384 arm at the one-pass-BN byte budget; "
                    "compare the official batch-256 record"})
        log(f"resweep384: {rec.get('value')}")
    return ok


def stage_int8_ab():
    rc, _ = run_logged("int8_ab", [sys.executable, "tools/int8_ab.py"],
                       3000)
    return rc == 0


def stage_flash_sweep():
    rc, _ = run_logged("flash_sweep",
                       [sys.executable, "tools/flash_sweep.py"], 3600)
    return rc == 0


def stage_longctx():
    rc, _ = run_logged("longctx",
                       [sys.executable, "tools/longctx_bench.py"], 2400)
    return rc == 0


def stage_mfu():
    rc, _ = run_logged("mfu", [sys.executable, "tools/mfu_probe.py"], 5400)
    return rc == 0


# The first-window session plan (ROUND5_NOTES items 1-10 EXCEPT the
# on-chip pytest tier, which stays manual), in VERDICT priority order:
# official bench and the silicon validation sweep first — the tunnel can
# die again at any minute — then the BERT roofline (ask#3), the resnet
# profile + BN-diet + batch-384 receipts, the A/Bs, and the LONG probes
# last (mfu is ~90 min, deliberately demoted from its old 3rd slot so a
# short window captures the higher-priority artifacts first).  Each
# stage's done-predicate reads the artifact it produces, so a
# wedge-shortened window resumes at the first unfinished stage on the
# next contact.
STAGES = [
    ("bench_quick", bench_quick_done, stage_bench_quick),
    ("bench", bench_done, stage_bench),
    ("validate", validation_done, stage_validate),
    ("profile_bert", lambda: _profile_done(artifact("PROFILE_BERT")),
     stage_profile_bert),
    ("profile_resnet", lambda: _profile_done(artifact("PROFILE_STEP")),
     stage_profile_resnet),
    ("bn_ab", bn_ab_done, stage_bn_ab),
    ("resweep384", resweep384_done, stage_resweep384),
    ("int8_ab", int8_ab_done, stage_int8_ab),
    ("flash_sweep", flash_sweep_done, stage_flash_sweep),
    ("longctx", longctx_done, stage_longctx),
    ("mfu", mfu_done, stage_mfu),
]


def main():
    n_probe = up_count = 0
    last_fail = 0.0
    log(f"watching for the TPU backend (probe every "
        f"{PROBE_INTERVAL_DOWN}s while down; {len(STAGES)} stages armed)")
    while True:
        n_probe += 1
        stages_done = {name: bool(done()) for name, done, _ in STAGES}
        try:
            up, detail = probe()  # probe holds the chip lock itself
        except ChipBusy:
            log("chip lock held by another process (driver bench?); "
                "yielding this cycle")
            write_status(up=None, probes=n_probe, up_probes=up_count,
                         stages_done=stages_done,
                         validation_done=stages_done["validate"],
                         bench_done=stages_done["bench"],
                         mfu_done=stages_done["mfu"],
                         detail="chip lock held; probe skipped")
            time.sleep(PROBE_INTERVAL_DOWN)
            continue
        with open(PROBE_LOG, "a") as f:
            f.write(json.dumps({"ts": ts(), "up": up,
                                "detail": detail}) + "\n")
        if up:
            up_count += 1
        write_status(up=up, probes=n_probe, up_probes=up_count,
                     stages_done=stages_done,
                     validation_done=stages_done["validate"],
                     bench_done=stages_done["bench"],
                     mfu_done=stages_done["mfu"], detail=detail)
        if up and not all(stages_done.values()) and \
                time.time() - last_fail > FAIL_BACKOFF:
            log(f"TPU is UP ({detail}); pending: "
                f"{[n for n, d in stages_done.items() if not d]}")
            ok = True
            for name, done, runner in STAGES:
                if done():
                    continue
                try:
                    # re-probe between stages: a dead tunnel must cost
                    # one 120s probe, not a stage's full timeout budget
                    alive, _ = probe()
                    if not alive:
                        log(f"tunnel lost before stage {name}; backing off")
                        ok = False
                        break
                    log(f"running stage {name}...")
                    st_ok = runner()
                except ChipBusy:
                    # the driver bench grabbed the chip between stages:
                    # yield the whole window, don't poke at a busy chip
                    log("chip lock taken (driver bench?); yielding the "
                        "rest of the stage window")
                    ok = False
                    break
                log(f"stage {name}: {'ok' if st_ok else 'FAILED/partial'}")
                ok = ok and st_ok
            if not ok:
                last_fail = time.time()
            stages_done = {name: bool(done()) for name, done, _ in STAGES}
            write_status(up=up, probes=n_probe, up_probes=up_count,
                         stages_done=stages_done,
                         validation_done=stages_done["validate"],
                         bench_done=stages_done["bench"],
                         mfu_done=stages_done["mfu"], detail=detail)
        done_all = all(stages_done.values())
        time.sleep(PROBE_INTERVAL_DONE if done_all else PROBE_INTERVAL_DOWN)


if __name__ == "__main__":
    main()
