"""Shared write protocol for on-chip measurement artifacts
(MFU_PROBE_<round>.json, LONGCTX_<round>.json, ...).

The contract (see .claude/skills/verify/SKILL.md "hardware artifacts are
merge-on-write"):
- a partial rerun (--configs / --lens retry after a transport blip) MERGES
  into the existing artifact — this run's rows replace their own keys,
  sibling rows survive (a retry once clobbered a full sweep's rows);
- a TPU-less process REFUSES to overwrite a platform=tpu artifact (a
  tunnel-down run or CPU smoke pointed at the default --out must not
  replace real rows with a skip/smoke record);
- writes are atomic (tmp+rename) and happen after every row, so a later
  hang cannot lose earlier results.

Rows should be self-describing (carry their own config/geometry fields):
merged rows may come from runs with different settings, and the row is
the only place that provenance survives.
"""
from __future__ import annotations

import json
import os

# Round stamp for every hardware artifact this tree produces.  Single
# source of truth: the watcher, validate sweep, MFU probe, long-context
# bench and chip profiler all derive their default --out from here, so a
# new round is one-line (or TPUMX_ROUND=rNN) instead of a five-file sweep.
ROUND = os.environ.get("TPUMX_ROUND", "r05")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def artifact(name, ext="json"):
    """Round-stamped artifact path at the repo root:
    artifact("MFU_PROBE") -> <repo>/MFU_PROBE_r05.json."""
    return os.path.join(_REPO, f"{name}_{ROUND}.{ext}")


def load_prior(path):
    """The existing artifact as a dict; {} if absent/corrupt."""
    try:
        with open(path) as f:
            prior = json.load(f)
        return prior if isinstance(prior, dict) else {}
    except (OSError, ValueError):
        return {}


def refuses_clobber(prior, platform):
    """True when THIS process (running on `platform`) must not overwrite
    the artifact `prior` (measured on real TPU)."""
    return platform != "tpu" and prior.get("platform") == "tpu"


def merge_prior_sections(record, prior, sections, require_platform=None):
    """Graft prior rows this run hasn't produced into record[section].
    This run's rows win on key collision.  require_platform: only merge
    from a prior artifact measured on that platform (pass the current
    platform so e.g. CPU-smoke rows never leak into a TPU artifact)."""
    if require_platform is not None and \
            prior.get("platform") != require_platform:
        return record
    for sect in sections:
        if isinstance(prior.get(sect), dict) and \
                isinstance(record.get(sect), dict):
            merged = dict(prior[sect])
            merged.update(record[sect])
            record[sect] = merged
    return record


def write_atomic(path, record):
    with open(path + ".tmp", "w") as f:
        json.dump(record, f, indent=1)
    os.replace(path + ".tmp", path)
