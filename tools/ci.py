"""Run the full test suite and fail LOUDLY if anything is red.

VERDICT r2 weak#2 post-mortem: a round once shipped with a failing test
because the suite stopped being run to completion.  This gate is the
snapshot-time check: `python tools/ci.py` exits nonzero with an
unmissable banner when any test fails, and prints per-tier timing so the
slowest tier stays visible.

Tiers: core (`-m "not slow"`, <5 min), slow (virtual-mesh parallelism,
full-model layout trains, op-audit sweep, native C++ tier), then the
example smokes.  `--core-only` runs just the first for a quick gate.
"""
from __future__ import annotations

import subprocess
import sys
import time

TIERS = [
    ("core", ["tests/", "-m", "not slow",
              "--deselect", "tests/test_examples.py"]),
    ("slow", ["tests/", "-m", "slow",
              "--deselect", "tests/test_examples.py"]),
    ("examples", ["tests/test_examples.py"]),
]


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--core-only", action="store_true",
                    help="run just the <5 min core tier")
    opts = ap.parse_args()  # unknown args fail fast, not silently run all
    tiers = TIERS[:1] if opts.core_only else TIERS
    results = []
    for name, args in tiers:
        t0 = time.time()
        proc = subprocess.run([sys.executable, "-m", "pytest", "-q", *args])
        results.append((name, proc.returncode, time.time() - t0))
    print()
    red = False
    for name, rc, dt in results:
        status = "PASS" if rc == 0 else "FAIL"
        red = red or rc != 0
        print(f"  {status}  {name:10s} {dt:7.1f}s")
    if red:
        print("\n" + "!" * 64)
        print("!!  TEST SUITE RED — do NOT snapshot/ship this state  !!")
        print("!" * 64)
        return 1
    print("\nall tiers green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
