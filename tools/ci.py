"""Run the full test suite and fail LOUDLY if anything is red.

VERDICT r2 weak#2 post-mortem: a round once shipped with a failing test
because the suite stopped being run to completion.  This gate is the
snapshot-time check: `python tools/ci.py` exits nonzero with an
unmissable banner when any test fails, and prints per-tier timing so the
slowest tier stays visible.

Tiers: unit (everything but examples) then the example smoke tier.
"""
from __future__ import annotations

import subprocess
import sys
import time

TIERS = [
    ("unit", ["tests/", "--deselect", "tests/test_examples.py"]),
    ("examples", ["tests/test_examples.py"]),
]


def main():
    results = []
    for name, args in TIERS:
        t0 = time.time()
        proc = subprocess.run([sys.executable, "-m", "pytest", "-q", *args])
        results.append((name, proc.returncode, time.time() - t0))
    print()
    red = False
    for name, rc, dt in results:
        status = "PASS" if rc == 0 else "FAIL"
        red = red or rc != 0
        print(f"  {status}  {name:10s} {dt:7.1f}s")
    if red:
        print("\n" + "!" * 64)
        print("!!  TEST SUITE RED — do NOT snapshot/ship this state  !!")
        print("!" * 64)
        return 1
    print("\nall tiers green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
