"""Run the full test suite and fail LOUDLY if anything is red.

VERDICT r2 weak#2 post-mortem: a round once shipped with a failing test
because the suite stopped being run to completion.  This gate is the
snapshot-time check: `python tools/ci.py` exits nonzero with an
unmissable banner when any test fails, and prints per-tier timing so the
slowest tier stays visible.

Tiers: lint — tools/tpumx_lint.py, the framework-aware two-phase static
analyzer (project index + call graph, then the rule passes) enforcing
the durability/determinism/sync-point/concurrency/telemetry/
hot-path-purity contracts on every line including branches no fault
schedule executes (docs/static_analysis.md; fastest tier, no device,
runs FIRST so a contract violation fails before any test time is spent,
and asserts LINT_BUDGET_SECONDS so the index phase can never silently
blow up tier runtime) — then core
(`-m "not slow"`, <5 min), slow (virtual-mesh parallelism,
full-model layout trains, op-audit sweep, native C++ tier), the example
smokes, chaos (the fault-injection durability tests re-run under a fixed
TPUMX_CHAOS_SEED, docs/robustness.md), native-asan — an
AddressSanitizer build+run of
`native/tpumx_io_test.cpp`, the one multithreaded-shared-state code the
project owns (threads + shared queues; the reference ran ASAN CI,
SURVEY §5.2 / VERDICT r5 missing#6) — then obs: a tiny instrumented
train loop run with TPUMX_TELEMETRY set, whose emitted JSONL must
validate against the telemetry schema AND the stable metric-name catalog
(tools/telemetry_report.py --validate; docs/observability.md — an
accidental metric rename fails this tier), plus the flight-recorder leg:
one chaos-crashed supervised run per failure class (hang, NaN streak,
crash, SIGTERM) must leave a schema-valid black box whose timeline links
injection -> detection -> decision, rendered by tools/blackbox_report.py
under a poisoned jax import — and soak: a supervised
training run under a fixed-seed randomized chaos schedule (hang, NaN
streak, crash-mid-save, torn write) that must finish with a verified
latest checkpoint, a finite loss, and ≥1 recorded restart, rollback and
watchdog fire (tpu_mx/supervisor.py; docs/robustness.md) — and serve: a
fixed-seed request storm against the serving runtime (tpu_mx/serving/,
docs/serving.md) under reject_storm, slow_decode_step and NaN-logits
chaos, which must end with ZERO lost requests, a schema-valid black box
per injected fault (rendered without jax), and catalog-valid serving
metrics.  `--core-only` runs just the first for a quick gate.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

TIERS = [
    ("core", ["tests/", "-m", "not slow",
              "--deselect", "tests/test_examples.py"], None),
    ("slow", ["tests/", "-m", "slow",
              "--deselect", "tests/test_examples.py"], None),
    ("examples", ["tests/test_examples.py"], None),
    # fault-injection tier: the durability/recovery tests re-run with a
    # FIXED chaos seed so every injected crash/tear/backoff byte boundary
    # is reproducible run-to-run (ISSUE 2; the core tier runs these too,
    # but under whatever seed the environment happens to carry)
    ("chaos", ["tests/test_checkpoint.py", "tests/test_elastic.py",
               "tests/test_supervisor.py", "tests/test_fleet.py",
               "-m", "not slow"], {"TPUMX_CHAOS_SEED": "20260804"}),
]


# Hard wall-clock budget for the whole-tree lint (index build included).
# The two-phase analyzer measures ~5 s on this host (ISSUE 10: phase 1
# index + phase 2 passes; was ~3 s lexical-only); the budget is sized to
# ride out CI-host scheduling noise while still failing LOUDLY if the
# index phase ever regresses to per-file re-parsing or superlinear call
# graph work — a silent 10x here would eat the whole tier's cheapness.
LINT_BUDGET_SECONDS = 15.0


def lint_tier():
    """Run the static contract checker over the default tree; any
    unsuppressed, non-baselined finding is a red tier, and so is blowing
    the LINT_BUDGET_SECONDS wall-clock budget (the index phase must stay
    cheap — this tier runs FIRST on every CI invocation).  JSON mode so
    the gate parses the count rather than scraping human output."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.time()
    try:
        run = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "tpumx_lint.py"),
             "--format", "json"],
            capture_output=True, text=True, timeout=120, cwd=repo)
    except subprocess.TimeoutExpired as e:
        print(f"  lint: timed out: {e}")
        return 1
    elapsed = time.time() - t0
    if elapsed > LINT_BUDGET_SECONDS:
        print(f"  lint: whole-tree run took {elapsed:.1f}s — over the "
              f"{LINT_BUDGET_SECONDS:.0f}s tier budget; the index phase "
              "has regressed (profile tools/lint/index.py before raising "
              "the budget)")
        return 1
    if run.returncode != 0:
        # surface the findings (re-rendered from JSON) in the CI log
        try:
            payload = json.loads(run.stdout)
            for f in payload.get("findings", []):
                print(f"  {f['path']}:{f['line']}: [{f['rule']}] "
                      f"{f['message']}")
            for e in payload.get("errors", []):
                print(f"  lint error: {e}")
        except ValueError:
            print((run.stdout or "") + (run.stderr or ""))
        return run.returncode or 1
    return 0


def native_asan():
    """Compile and run the native io C++ unit tier under
    -fsanitize=address.  Returns a process-style rc (0 = green).  The
    tpumx_io_test source skips its RLIMIT_AS observable under ASAN (the
    shadow reservation needs terabytes of address space); everything
    else — threaded decode, RecordIO scan, det label bounds — runs with
    heap/use-after-free checking armed."""
    if shutil.which("g++") is None:
        print("  native-asan: g++ not found — cannot run the sanitizer "
              "tier (counts as FAIL: the gate must not pass vacuously)")
        return 1
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "native", "tpumx_io_test.cpp")
    try:
        with tempfile.TemporaryDirectory() as d:
            binary = os.path.join(d, "tpumx_io_test_asan")
            cc = subprocess.run(
                ["g++", "-O1", "-g", "-std=c++17", "-fsanitize=address",
                 src, "-o", binary, "-ljpeg", "-lpthread"],
                capture_output=True, text=True, timeout=300)
            if cc.returncode != 0:
                print(f"  native-asan: compile failed:\n{cc.stderr[-2000:]}")
                return cc.returncode or 1
            run = subprocess.run([binary], capture_output=True, text=True,
                                 timeout=300)
            out = (run.stdout or "") + (run.stderr or "")
            if run.returncode != 0 or "ALL PASS" not in out:
                print(f"  native-asan: run failed (rc={run.returncode}):\n"
                      f"{out[-3000:]}")
                return run.returncode or 1
    except subprocess.TimeoutExpired as e:
        # a wedged compile or a hung test binary (e.g. the threaded-decode
        # deadlock this tier exists to police) must surface as a FAIL row
        # in the results table, not crash the driver
        print(f"  native-asan: timed out: {e}")
        return 1
    return 0


# The obs tier's workload: every instrumented subsystem the acceptance
# criteria name must emit — the compiled train step (recompiles + step
# latency), the fusion engine (flushes), and the durable checkpoint path
# (save latency histogram).  Runs on the CPU backend like the test suite.
OBS_SCRIPT = """
import os
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tpu_mx as mx
from tpu_mx import nd, engine, elastic, gluon, telemetry
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep

net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
net.initialize()
net(nd.ones((1, 4)))
X = np.random.RandomState(0).rand(16, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)
step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         mx.optimizer.create("sgd", learning_rate=0.1))
for _ in range(4):
    step.step(nd.array(X), nd.array(Y))
step.sync_to_net()
telemetry.flush()  # mid-run append-mode snapshot

x = nd.array(np.ones((8, 8), np.float32))
for _ in range(3):
    with engine.bulk(8):
        nd.tanh(x * 1.5 + 0.5).wait_to_read()

prefix = os.path.join(os.path.dirname(os.environ["TPUMX_TELEMETRY"]), "ck")
elastic.save_checkpoint(prefix, 0, net=net)
assert elastic.latest_checkpoint(prefix)[0] == 0
telemetry.flush(final=True)  # atomic final snapshot
"""

OBS_REQUIRED = ("fusion.flushes", "checkpoint.save_seconds",
                "train_step.recompiles", "train_step.steps")


# The obs tier's flight-recorder leg (ISSUE 7): chaos-crash a supervised
# run once per failure class — hang, NaN streak, crash-mid-save, SIGTERM
# preemption — and assert each leaves a readable, schema-valid black box
# whose timeline links injection -> detection -> supervisor decision by
# shared (epoch, step, generation) trace context.  The rendering check
# (blackbox_report.py must work WITHOUT jax) runs in the driver below.
BLACKBOX_SCRIPT = """
import json
import os
import signal
import time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, gluon, nd, tracing
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep
from tpu_mx.supervisor import Supervisor

D = os.environ["TPUMX_BLACKBOX_DIR"]
R = np.random.RandomState(0)
X = R.rand(32, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)
NB, BS = 4, 8


def build():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 4)))
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd", learning_rate=0.05))
    return net, step


def supervised(tag, fault, **sup_kw):
    tracing.reset()
    prefix = os.path.join(D, tag)
    net, step = build()

    def save_fn(e):
        step.sync_to_net()
        elastic.save_checkpoint(prefix, e, net=net)

    def restore_fn():
        e = elastic.auto_resume(prefix, net=net)
        step.sync_from_net()
        return e

    sup = Supervisor(save_fn=save_fn, restore_fn=restore_fn,
                     blackbox=prefix, backoff=0.05, cooldown=0.0, **sup_kw)

    def epoch_fn(epoch):
        for i in range(NB):
            xb, yb = X[i * BS:(i + 1) * BS], Y[i * BS:(i + 1) * BS]
            sup.step(lambda: step.step(nd.array(xb), nd.array(yb)))

    with chaos.enable(**fault):
        res = sup.run(epoch_fn, 0, 3)
    assert res.ok, (tag, res.as_dict())
    path = tracing.blackbox_path(prefix)
    assert os.path.exists(path), (tag, "no black box dumped")
    box = json.load(open(path))
    tracing.validate_blackbox(box)
    return box


def chain(box, kind, *decisions):
    # injection -> detection -> decision, joined on (epoch, generation):
    # a NaN streak's divergence is declared a step after the first
    # poisoned loss, so the step is recorded but not part of the join
    evs = box["events"]
    inj = [e for e in evs if e["event"] == "chaos.inject"
           and e["data"]["kind"] == kind]
    assert inj, (kind, [e["event"] for e in evs])
    key = (inj[0]["epoch"], inj[0]["generation"])
    assert inj[0]["step"] is not None, inj[0]
    got = [e["event"] for e in evs
           if (e["epoch"], e["generation"]) == key]
    for want in decisions:
        assert want in got, (kind, want, got)


box = supervised("bb-hang", dict(hang_step=6, hang_seconds=30, seed=1),
                 deadline=2.0, compile_grace=60.0)
chain(box, "hang", "supervisor.watchdog_fire", "supervisor.classify",
      "supervisor.restart")

box = supervised("bb-nan", dict(nan_after=NB + 2, nan_streak=2, seed=1),
                 skip_limit=1)
chain(box, "nan", "supervisor.sentinel_skip", "supervisor.classify",
      "supervisor.rollback")

box = supervised("bb-crash",
                 dict(crash_after_bytes=200, match=".params", seed=1))
chain(box, "crash", "supervisor.classify", "supervisor.restart")

# SIGTERM preemption: the handler's emergency save + black box, no exit
tracing.reset()
prefix = os.path.join(D, "bb-sigterm")
net, step = build()


def emergency():
    step.sync_to_net()
    elastic.save_checkpoint(prefix, 0, net=net)


handle = ckpt.preemption_handler(emergency, exit=False,
                                 blackbox_prefix=prefix)
for i in range(2):
    step.step(nd.array(X[:BS]), nd.array(Y[:BS]))
os.kill(os.getpid(), signal.SIGTERM)
for _ in range(100):  # delivery is prompt but asynchronous
    if handle.triggered:
        break
    time.sleep(0.05)
assert handle.triggered and handle.save_ok, (handle.triggered,
                                             handle.save_ok)
box = json.load(open(tracing.blackbox_path(prefix)))
tracing.validate_blackbox(box)
names = [e["event"] for e in box["events"]]
assert "checkpoint.preemption" in names, names
assert "checkpoint.save" in names, names
print("BLACKBOX OK", flush=True)
"""

# what the rendered report must contain per failure-class box: the
# injection, the detection and the matching decision in prose
BLACKBOX_EXPECT = {
    "bb-hang": ("chaos hang injected", "watchdog fired", "restart #"),
    "bb-nan": ("chaos nan injected", "sentinel skipped batch",
               "rollback #"),
    "bb-crash": ("chaos crash injected", "classified transient",
                 "restart #"),
    "bb-sigterm": ("checkpoint.preemption", "save_ok=True"),
}


# The soak tier's workload: a REAL supervised training run under a
# fixed-seed randomized fault schedule — hang, NaN streak, crash-mid-save,
# torn write — that must end with a verified latest checkpoint, a finite
# loss, and every recovery path provably taken (ISSUE 4 acceptance) —
# followed by the deterministic-resume leg (ISSUE 5 acceptance): a
# capsule-enabled run chaos-crashed mid-epoch must reproduce the
# uninterrupted run's per-step loss trajectory and final weights EXACTLY,
# with a zero resume_step_gap.
# The schedule is derived from TPUMX_CHAOS_SEED so a red run reproduces.
SOAK_SCRIPT = """
import contextlib
import math
import os
import random
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, gluon, nd, telemetry
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep
from tpu_mx.supervisor import Supervisor

SEED = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
rng = random.Random(SEED)
prefix = os.path.join(os.path.dirname(os.environ["TPUMX_TELEMETRY"]),
                      "soak")

net = nn.HybridSequential()
net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
net.initialize()
net(nd.ones((1, 4)))
step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         mx.optimizer.create("sgd", learning_rate=0.05))
R = np.random.RandomState(SEED)
X = R.rand(64, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)
NB, BS, EPOCHS = 4, 16, 10

# the randomized fault schedule (all positions seed-derived).  Ranges
# keep the script's own assertions satisfiable for EVERY seed: the torn
# epoch stays below EPOCHS-1 (the final epoch must verify as latest) and
# the NaN streak starts early enough to fit inside its epoch (a streak
# split across the chaos scope would disarm after one skip — no rollback)
hang_epoch = rng.randint(2, 3)
nan_epoch = rng.randint(4, 5)
crash_epoch = rng.randint(6, 7)
torn_epoch = rng.randint(8, EPOCHS - 2)
EPOCH_FAULTS = {
    hang_epoch: dict(hang_step=rng.randint(1, NB), seed=SEED),
    nan_epoch: dict(nan_after=rng.randint(1, NB - 1), nan_streak=2,
                    seed=SEED),
}
SAVE_FAULTS = {
    crash_epoch: dict(crash_after_bytes=200, match=".params", seed=SEED),
    torn_epoch: dict(torn_write=120, match=".params", seed=SEED),
}
print("SOAK schedule: hang@%d nan@%d crash@%d torn@%d" %
      (hang_epoch, nan_epoch, crash_epoch, torn_epoch), flush=True)


def save_fn(epoch):
    faults = SAVE_FAULTS.pop(epoch, None)  # pop: the retried save is clean
    with (chaos.enable(**faults) if faults else contextlib.nullcontext()):
        step.sync_to_net()
        elastic.save_checkpoint(prefix, epoch, net=net)


def restore_fn():
    start = elastic.auto_resume(prefix, net=net)
    step.sync_from_net()
    return start


sup = Supervisor(save_fn=save_fn, restore_fn=restore_fn,
                 deadline=20.0, compile_grace=60.0, max_restarts=5,
                 max_rollbacks=3, skip_limit=1, backoff=0.05,
                 cooldown=0.0, seed=SEED, blackbox=prefix)


def epoch_fn(epoch):
    faults = EPOCH_FAULTS.pop(epoch, None)
    with (chaos.enable(**faults) if faults else contextlib.nullcontext()):
        for i in range(NB):
            xb, yb = X[i * BS:(i + 1) * BS], Y[i * BS:(i + 1) * BS]
            sup.step(lambda: step.step(nd.array(xb), nd.array(yb)))


res = sup.run(epoch_fn, begin_epoch=0, num_epoch=EPOCHS)
print("SOAK result:", res.as_dict(), flush=True)
assert res.status == "completed", res.as_dict()
# ≥1 recorded restart, rollback, watchdog fire, skipped batch (acceptance)
assert res.restarts >= 2, res.as_dict()       # hang + crash-mid-save
assert res.rollbacks >= 1, res.as_dict()      # NaN streak past the budget
assert res.watchdog_fires >= 1, res.as_dict()
assert res.batches_skipped >= 1, res.as_dict()
# finite final loss, verified latest checkpoint
assert res.final_loss is not None and math.isfinite(res.final_loss)
epoch, path = elastic.latest_checkpoint(prefix)
assert epoch == EPOCHS - 1, (epoch, path)
assert ckpt.verify_checkpoint(prefix, epoch)[0] == "verified"
# the torn epoch is on disk but detectably corrupt (manifest caught it)
assert ckpt.verify_checkpoint(prefix, torn_epoch)[0] == "corrupt"
assert ckpt.newest_verified_epoch(prefix) == EPOCHS - 1

# ---- flight-recorder leg (ISSUE 7 acceptance): every injected fault is
# linked to its detection and the supervisor's decision by shared
# (epoch, generation) trace context, in a schema-valid black box.  The
# per-recovery boxes were dumped during the run; this final audit dump
# captures the WHOLE timeline (the ring still holds it) including the
# torn write, whose detection only happens at the verify above.
import json as _json
from tpu_mx import tracing
bb_path = tracing.dump_blackbox(prefix, reason="soak post-run audit")
bb = _json.load(open(bb_path))
tracing.validate_blackbox(bb)
EVS = bb["events"]


def correlated(kind, *names):
    inj = [e for e in EVS if e["event"] == "chaos.inject"
           and e["data"]["kind"] == kind]
    assert inj, (kind, sorted({e["event"] for e in EVS}))
    key = (inj[0]["epoch"], inj[0]["generation"])
    got = [e["event"] for e in EVS if (e["epoch"], e["generation"]) == key]
    for n in names:
        assert n in got, (kind, n, got)


correlated("hang", "supervisor.watchdog_fire", "supervisor.classify",
           "supervisor.restart")
correlated("nan", "supervisor.sentinel_skip", "supervisor.classify",
           "supervisor.rollback")
correlated("crash", "supervisor.classify", "supervisor.restart")
# torn write: no exception at injection time — the manifest verification
# above is the detection, and both are on the same timeline
assert any(e["event"] == "chaos.inject"
           and e["data"]["kind"] == "torn_write" for e in EVS)
assert any(e["event"] == "checkpoint.verify"
           and e["data"].get("status") == "corrupt" for e in EVS)
assert telemetry.get("tracing.blackbox_dumps").value >= 3  # per recovery
print("SOAK blackbox leg OK", flush=True)

# ---- deterministic-resume leg (ISSUE 5 acceptance): a chaos-crashed-
# then-capsule-resumed run must reproduce the uninterrupted fixed-seed
# run's per-step loss trajectory and final weights EXACTLY — not just
# "finite and completed".  Capsules restore the RNG streams, the data
# iterator's shuffle/cursor and the mid-epoch train state, so the
# trajectories are compared with ==, no tolerance.
from tpu_mx import resume as tres
from tpu_mx import random as trandom


def det_build(seed):
    trandom.seed(seed)
    n = nn.HybridSequential()
    n.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    n.initialize()
    n(nd.ones((1, 4)))
    s = CompiledTrainStep(n, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("sgd", learning_rate=0.05))
    it = mx.io.NDArrayIter(X, Y, batch_size=BS, shuffle=True,
                           last_batch_handle="discard", seed=seed)
    return n, s, it


def det_run(tag, crash_at=None):
    pfx = prefix + "-det-" + tag
    net, step, it = det_build(123)
    mgr = tres.CapsuleManager(pfx, iters=[it], state=step, interval=1)
    det_sup = Supervisor(capsule=mgr, backoff=0.01, seed=0)

    def det_save(e):
        step.sync_to_net()
        elastic.save_checkpoint(pfx, e, net=net, capsule=mgr)

    def det_restore():
        e = elastic.auto_resume(pfx, net=net)
        step.sync_from_net()
        return e

    det_sup.save_fn, det_sup.restore_fn = det_save, det_restore
    losses = {}

    def det_epoch(epoch):
        if not det_sup.resume_step(epoch):
            it.reset()
        for batch in it:
            def one(b=batch):
                v = float(step.step(b.data[0], b.label[0]).asnumpy().mean())
                losses[(epoch, det_sup.step_in_epoch + 1)] = v
                return v
            det_sup.step(one)

    ctx = chaos.enable(crash_at_step=crash_at, seed=SEED) if crash_at \
        else contextlib.nullcontext()
    with ctx:
        r = det_sup.run(det_epoch, 0, 3)
    assert r.ok, r.as_dict()
    step.sync_to_net()
    return losses, [p.data().asnumpy() for p in
                    net.collect_params().values()], r


det_losses_a, det_w_a, _ = det_run("a")
det_losses_b, det_w_b, det_res_b = det_run("b", crash_at=rng.randint(5, 10))
assert det_res_b.restarts >= 1, det_res_b.as_dict()
assert det_losses_a == det_losses_b, (det_losses_a, det_losses_b)
for wa, wb in zip(det_w_a, det_w_b):
    assert np.array_equal(wa, wb), "post-recovery weights diverged"
# the soak tier FAILS if the resume left a replay gap (must be 0 under
# capsules — an exact-batch or exact-replay resume, never lost batches)
assert telemetry.get("resume.resume_step_gap").value == 0
print("SOAK deterministic-resume leg OK", flush=True)
telemetry.flush(final=True)
print("SOAK OK", flush=True)
"""

# "supervisor" / "resume" are telemetry_report require-presets: the
# supervisor recovery counters (restarts/rollbacks/watchdog_fires/
# batches_skipped — the degraded gauge is rightly 0 on a healthy soak)
# and the deterministic-resume counters (capsules written + a restore
# that actually went through the capsule path; the resume_step_gap
# gauge must be 0 and is asserted inside the soak script itself)
SOAK_REQUIRED = ("supervisor", "resume", "chaos.injections",
                 "checkpoint.corrupt_detected", "train_step.steps",
                 "tracing.blackbox_dumps")


# The soak tier's membership-churn leg (ISSUE 17): a two-member fleet in
# one process (the single-controller convention — member 0 drives the
# model on the full global batch; member 1 is a logical peer kept alive
# by a heartbeat thread, exactly what a real worker's beat loop does).
# The seeded schedule partitions member 1 (chaos `partition_worker`:
# beats suppressed, process alive) so its lease expires mid-epoch — the
# supervisor classifies the resulting MembershipChange as `membership`,
# reshards dp=2 -> dp=1 from the last verified manifest + capsule, and
# later admits the healed member back at the next epoch (reshard up).
# A second window SIGTERMs the training rank mid-step (chaos
# `preempt_worker_at_step`) — classified and survived, not fatal.
# Hard assertions: the churn run consumes the IDENTICAL global
# sample-id ledger as the uninterrupted oracle (zero skipped, zero
# duplicated), losses/weights match to float-reduction tolerance
# (dp=1 and dp=2 reassociate the batch sum — bitwise equality across
# the world change is impossible BY MEASUREMENT, ~1e-9), the no-train
# reshard round-trip dp=2 -> dp=1 -> dp=2 is BIT-exact, and the run
# ends completed with a verified latest epoch.
FLEET_SCRIPT = """
import math
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
import random
import signal
import threading
import time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tpu_mx as mx
from tpu_mx import checkpoint as ckpt, elastic, gluon, nd, telemetry
from tpu_mx import random as trandom
from tpu_mx import resume as tres
from tpu_mx.contrib import chaos
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep, make_mesh
from tpu_mx.parallel.fleet import Fleet
from tpu_mx.supervisor import Supervisor

assert jax.device_count() >= 2, jax.devices()
SEED = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
rng = random.Random(SEED)
root = os.path.dirname(os.environ["TPUMX_TELEMETRY"])
prefix = os.path.join(root, "fleet-ck")

R = np.random.RandomState(SEED)
X = R.rand(64, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)
BS, NB, EPOCHS, LEASE = 16, 4, 8, 1.0

# seeded churn schedule: partition early (heal = next epoch), preempt
# well after the rejoin so the chaos windows never overlap
PART_EPOCH, PART_STEP = rng.randint(1, 2), rng.randint(1, NB)
PREEMPT_EPOCH, PREEMPT_STEP = rng.randint(4, 6), rng.randint(1, NB)
print("FLEET schedule: partition@(%d,%d) preempt@(%d,%d)" %
      (PART_EPOCH, PART_STEP, PREEMPT_EPOCH, PREEMPT_STEP), flush=True)


# chaos preempts with a real SIGTERM; this harness must survive it the
# way a dying rank's peers do — as a WorkerFailure out of the step
def _on_term(sig, frame):
    raise elastic.WorkerFailure("preempted: SIGTERM mid-step")


signal.signal(signal.SIGTERM, _on_term)


def build_net():
    trandom.seed(123)
    n = nn.HybridSequential(prefix="fl_")
    n.add(nn.Dense(8, in_units=4, activation="relu", prefix="fc1_"))
    n.add(nn.Dense(2, in_units=8, prefix="fc2_"))
    n.initialize()
    n(nd.ones((1, 4)))
    return n


def make_step(world):
    mesh = make_mesh({"dp": 2}) if world >= 2 else \\
        make_mesh({"dp": 1}, devices=jax.devices()[:1])
    n = build_net()
    s = CompiledTrainStep(n, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("sgd", learning_rate=0.05),
                          mesh=mesh)
    return n, s


def make_iter():
    return mx.io.NDArrayIter(X, Y, batch_size=BS, shuffle=True,
                             last_batch_handle="discard", seed=123)


def weights(n):
    return [p.data().asnumpy() for p in n.collect_params().values()]


# ---- oracle: the uninterrupted fixed-seed run, dp=2 throughout ----
o_net, o_step = make_step(2)
o_it = make_iter()
o_ledger, o_losses = {}, {}
for epoch in range(EPOCHS):
    o_it.reset()
    for i, batch in enumerate(o_it):
        o_ledger[(epoch, i + 1)] = tuple(
            int(v) for v in o_it.global_batch_ids())
        o_losses[(epoch, i + 1)] = float(
            o_step.step(batch.data[0], batch.label[0]).asnumpy().mean())
o_step.sync_to_net()
o_w = weights(o_net)
print("FLEET oracle done", flush=True)

# ---- the churn run ----
f0 = Fleet(os.path.join(root, "fleet"), member=0, controller=True,
           lease=LEASE)
f0.advance(world=[0, 1], reason="launch")
f0.join()
f1 = Fleet(os.path.join(root, "fleet"), member=1, lease=LEASE)
f1.join()

stop_beats = threading.Event()


def beat_loop():  # member 1's liveness, decoupled from the train loop
    while not stop_beats.is_set():
        f1.heartbeat()
        time.sleep(LEASE / 10.0)


threading.Thread(target=beat_loop, daemon=True).start()

H = {}
H["net"], H["step"] = make_step(2)
it = make_iter()
mgr = tres.CapsuleManager(prefix, iters=[it], state=H["step"], interval=1,
                          fleet=f0)


def save_fn(epoch):
    H["step"].sync_to_net()
    elastic.save_checkpoint(prefix, epoch, net=H["net"], capsule=mgr)


def restore_fn():
    # the membership branch acks the new epoch BEFORE restoring, so the
    # adopted world size here is the post-churn one — rebuild the step
    # on the new mesh and point the capsule at it (load_state_dict then
    # re-places every leaf: the reshard seam)
    H["net"], H["step"] = make_step(max(1, f0.acked_world_size))
    mgr.state = H["step"]
    e = elastic.auto_resume(prefix, net=H["net"])
    H["step"].sync_from_net()
    return e


sup = Supervisor(save_fn=save_fn, restore_fn=restore_fn, capsule=mgr,
                 fleet=f0, deadline=30.0, compile_grace=60.0,
                 max_restarts=4, backoff=0.05, cooldown=0.0, seed=SEED,
                 blackbox=prefix)

ledger, losses = {}, {}
open_ctx, fired = [], set()


def epoch_fn(epoch):
    if epoch == PART_EPOCH + 1 and "part" in fired and "heal" not in fired:
        fired.add("heal")  # partition heals: member 1's beats resume
        open_ctx.pop().__exit__(None, None, None)
        assert f0.wait_member(1, timeout=10), "healed member never beat"
    if not sup.resume_step(epoch):
        it.reset()
    for batch in it:
        nxt = sup.step_in_epoch + 1
        if epoch == PART_EPOCH and nxt >= PART_STEP and "part" not in fired:
            fired.add("part")
            c = chaos.enable(partition_worker=1, seed=SEED)
            c.__enter__()
            open_ctx.append(c)
            time.sleep(LEASE * 1.5)  # outlive member 1's lease
        if epoch == PREEMPT_EPOCH and nxt >= PREEMPT_STEP \\
                and "pre" not in fired:
            fired.add("pre")
            c = chaos.enable(preempt_worker_at_step=1, preempt_rank=0,
                             seed=SEED)
            c.__enter__()
            open_ctx.append(c)

        def one(b=batch):
            v = float(H["step"].step(b.data[0], b.label[0])
                      .asnumpy().mean())
            k = (epoch, sup.step_in_epoch + 1)
            ledger[k] = tuple(int(x) for x in it.global_batch_ids())
            losses[k] = v
            return v

        sup.step(one)


try:
    res = sup.run(epoch_fn, begin_epoch=0, num_epoch=EPOCHS)
finally:
    stop_beats.set()
    while open_ctx:
        open_ctx.pop().__exit__(None, None, None)

print("FLEET result:", res.as_dict(), flush=True)
assert res.status == "completed", res.as_dict()
assert fired >= {"part", "heal", "pre"}, fired
assert res.restarts >= 1, res.as_dict()  # the preempt (not membership)

# exact replay: the churn run consumed the IDENTICAL batch sequence
assert set(ledger) == set(o_ledger), (len(ledger), len(o_ledger))
assert ledger == o_ledger, "sample-id ledger diverged from the oracle"
for epoch in range(EPOCHS):  # zero skipped, zero duplicated
    ids = sorted(i for (e, s), v in ledger.items() if e == epoch
                 for i in v)
    assert ids == list(range(len(X))), (epoch, ids[:8])

# loss-curve/weight parity: gated numerically — dp=1 and dp=2 psums
# reassociate the batch sum (measured ~1e-9), bitwise across the world
# change is not a sound gate
for k in sorted(o_losses):
    assert math.isclose(losses[k], o_losses[k],
                        rel_tol=1e-4, abs_tol=1e-6), \\
        (k, losses[k], o_losses[k])
H["step"].sync_to_net()
for a, b in zip(o_w, weights(H["net"])):
    assert np.allclose(a, b, rtol=1e-5, atol=1e-6), "weights diverged"

# membership accounting: >=2 reshards (down + up), >=1 rejoin, the lost
# worker counted, and the epoch gauge moved past the launch generation
assert telemetry.get("fleet.reshards").value >= 2
assert telemetry.get("fleet.rejoins").value >= 1
assert telemetry.get("fleet.lost_workers").value >= 1
assert telemetry.get("fleet.membership_epoch").value >= 3

# completed with a verified latest epoch
final_epoch, _path = elastic.latest_checkpoint(prefix)
assert final_epoch == EPOCHS - 1, final_epoch
assert ckpt.verify_checkpoint(prefix, final_epoch)[0] == "verified"

# the reshard seam itself is lossless: a no-train round trip back onto
# the original mesh is BIT-exact
def flat(sd, pre="", out=None):
    out = {} if out is None else out
    if isinstance(sd, dict):
        for k2 in sorted(sd):
            flat(sd[k2], pre + "/" + str(k2), out)
    else:
        try:
            out[pre] = np.asarray(sd)
        except Exception:
            pass
    return out


sd_f = H["step"].state_dict()
_n1, s1 = make_step(1)
s1.load_state_dict(sd_f)
_n2, s2 = make_step(2)
s2.load_state_dict(s1.state_dict())
fa, fb = flat(sd_f), flat(s2.state_dict())
assert set(fa) == set(fb)
for k in fa:
    assert np.array_equal(fa[k], fb[k]), k
print("FLEET reshard round-trip bit-exact OK", flush=True)
telemetry.flush(final=True)
print("FLEET OK", flush=True)
"""

# "fleet" is the telemetry_report require-preset (membership_epoch +
# reshards + rejoins all nonzero); resume/chaos/train_step gate that the
# churn actually rode the capsule path under injected faults
FLEET_REQUIRED = ("fleet", "resume", "chaos.injections",
                  "train_step.steps")


# The soak tier's STRAGGLER sub-leg (ISSUE 18): a real 2-worker fleet
# under `tools/launch.py --supervise` with the `slow_worker_rank` chaos
# knob delaying every rank-1 step inside the measured data_wait window,
# run through BOTH churn shapes (mid-step SIGTERM preempt -> evict ->
# restart -> rejoin, and partition -> lease expiry -> heal -> rejoin).
# Each worker trains a tiny real model through CompiledTrainStep — the
# phase events the cross-rank attribution correlates come from the
# production train-step path, not a simulation.  Gates: the controller's
# fleet.step_skew_seconds gauge moved, the windowed detector names the
# injected rank with the injected dominant phase in the fleet black box,
# `fleet_report --validate` passes on that box under POISONED jax (the
# report tools never boot the accelerator stack), and
# `telemetry_report --merge --require fleet_obs` holds the aggregation
# identity across the controller + per-worker registries.
STRAGGLER_WORKER = """
import os
import sys
import threading
import time

sys.path.insert(0, os.environ["TPUMX_REPO"])
member = int(os.environ["TPUMX_FLEET_MEMBER"])
# per-rank telemetry sink: workers inherit the controller's env, and a
# shared JSONL would interleave two processes' appends
os.environ["TPUMX_TELEMETRY"] = os.path.join(
    os.environ["TPUMX_CI_DIR"], "worker-%d.jsonl" % member)
# the CPU backend cannot run cross-process collectives: drop the
# coordinator env before the tpu_mx import boots jax.distributed (also
# keeps XLA's preemption notifier off the chaos SIGTERM)
for k in ("TPUMX_COORDINATOR", "TPUMX_NUM_PROC", "TPUMX_PROC_ID"):
    os.environ.pop(k, None)

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tpu_mx as mx
from tpu_mx import gluon, nd, telemetry, tracing
from tpu_mx import random as trandom
from tpu_mx.contrib import chaos
from tpu_mx.elastic import WorkerFailure
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep
from tpu_mx.parallel.fleet import Fleet, MembershipChange

LEASE = float(os.environ.get("TPUMX_FLEET_LEASE", "2.0"))
if os.environ.get("TPUMX_CI_SCENARIO") == "partition" and member == 1:
    # armed programmatically, NOT via TPUMX_CHAOS: the partition must
    # HEAL mid-run, which a parse-once env knob cannot express
    cfg = chaos._Config(partition_worker=1, slow_worker_rank=1,
                        slow_worker_seconds=0.2)
    chaos._config = cfg

    def _heal():
        with cfg.lock:
            cfg.partition_worker = None
    # heal just past the lease horizon: ONE eviction cycle (expire ->
    # evict -> heal -> rejoin), not a churn storm
    threading.Timer(LEASE * 1.2, _heal).start()

f = Fleet.from_env()
f.join()
f.await_admission(timeout=60)

trandom.seed(7)
net = nn.HybridSequential(prefix="sw_")
net.add(nn.Dense(4, in_units=4, activation="relu", prefix="fc1_"))
net.add(nn.Dense(2, in_units=4, prefix="fc2_"))
net.initialize()
net(nd.ones((1, 4)))
step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         mx.optimizer.create("sgd", learning_rate=0.05))
R = np.random.RandomState(3)
X = R.rand(8, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)

STEPS = int(os.environ.get("TPUMX_CI_STEPS", "24"))
s = 0
deadline = time.monotonic() + 120
while s < STEPS and time.monotonic() < deadline:
    try:
        f.on_step()
    except MembershipChange:
        try:
            f.ack()
            f.shard()
        except WorkerFailure:
            # evicted (lease expired while partitioned): rejoin at the
            # next epoch instead of dying
            f.join()
            f.await_admission(timeout=60)
        continue
    s += 1
    # both ranks walk the SAME (epoch, step) grid — the cross-rank
    # correlation joins on these keys (+ the membership generation the
    # fleet stamps into the trace context).  The baseline pace keeps
    # the ranks within the same generation window long enough to
    # correlate: an unpaced fast rank would finish the whole grid
    # before the chaos-slowed one left step 2, and a step only ONE rank
    # observed has no skew
    tracing.set_context(epoch=s // 8, step=s % 8)
    step.step(nd.array(X), nd.array(Y))
    time.sleep(0.15)
telemetry.flush(final=True)
f.leave()
print("WORKER DONE", member, flush=True)
"""


# The soak tier's SDC sub-leg (ISSUE 20): a real 3-worker fleet of
# IDENTICAL replicas (same init seed, same data grid — cross-replica
# fingerprints must agree bit-exactly) under `tools/launch.py
# --supervise`, with the `bitflip_param_at_step` chaos knob flipping one
# mantissa bit in rank 1's committed parameters.  The next fingerprint
# vote must name rank 1 as the minority: rank 1 quarantines itself and
# dies, the launcher refuses the restart (permanent, unlike a transient
# eviction), and the survivors roll back to the last VERIFIED weights
# and replay.  Gates: the quarantine record exists and rank 1 was never
# respawned, the survivors' final weights are bit-equal to an
# uninjected fixed-seed run, the fleet black box carries a schema-valid
# corruption verdict readable under POISONED jax, and the merged
# telemetry passes `--require integrity`.
#
# TPUMX_CI_BASELINE=1 runs the SAME training loop with no fleet, no
# integrity plane and no chaos — the bit-equality oracle.  Keeping both
# arms in one script is load-bearing: the comparison only proves the
# rollback path exact if the two arms share every line of the loop.
SDC_WORKER = """
import os
import sys
import time

sys.path.insert(0, os.environ["TPUMX_REPO"])
baseline = os.environ.get("TPUMX_CI_BASELINE") == "1"
member = int(os.environ.get("TPUMX_FLEET_MEMBER", "-1"))
if not baseline:
    # per-rank telemetry sink: workers inherit the controller's env, and
    # a shared JSONL would interleave the processes' appends
    os.environ["TPUMX_TELEMETRY"] = os.path.join(
        os.environ["TPUMX_CI_DIR"], "worker-%d.jsonl" % member)
for k in ("TPUMX_COORDINATOR", "TPUMX_NUM_PROC", "TPUMX_PROC_ID"):
    os.environ.pop(k, None)

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import tpu_mx as mx
from tpu_mx import gluon, nd, telemetry
from tpu_mx import random as trandom
from tpu_mx.gluon import nn
from tpu_mx.parallel import CompiledTrainStep


def build():
    # identical replicas: every rank (and the uninjected baseline) seeds
    # the SAME init and walks the SAME fixed batch
    trandom.seed(11)
    np.random.seed(11)
    net = nn.HybridSequential(prefix="sdc_")
    net.add(nn.Dense(4, in_units=4, activation="relu", prefix="fc1_"))
    net.add(nn.Dense(2, in_units=4, prefix="fc2_"))
    net.initialize()
    net(nd.ones((1, 4)))
    step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mx.optimizer.create("sgd",
                                                 learning_rate=0.05))
    return net, step


R = np.random.RandomState(3)
X = R.rand(8, 4).astype(np.float32)
Y = (X.sum(1) > 2).astype(np.float32)
STEPS = int(os.environ.get("TPUMX_CI_STEPS", "16"))


def snapshot(net, step):
    step.sync_to_net()
    return {k: p.data().asnumpy().copy()
            for k, p in net.collect_params().items()}


def dump_final(net, step, tag):
    step.sync_to_net()
    out = {k: p.data().asnumpy()
           for k, p in net.collect_params().items()}
    np.savez(os.path.join(os.environ["TPUMX_CI_DIR"],
                          "final-%s.npz" % tag), **out)


if baseline:
    net, step = build()
    for _ in range(STEPS):
        step.step(nd.array(X), nd.array(Y))
    dump_final(net, step, "baseline")
    print("WORKER DONE baseline", flush=True)
    sys.exit(0)

from tpu_mx.elastic import WorkerFailure
from tpu_mx.parallel.fleet import Fleet, MembershipChange
from tpu_mx.parallel.integrity import DataCorruption, IntegrityMonitor

net, step = build()
# compile BEFORE the lease clock starts: the first jit build takes
# longer than a CI-sized lease, and a rank that joins then disappears
# into XLA for that long reads as partitioned
step.aot_compiled(nd.array(X), nd.array(Y))
f = Fleet.from_env()
f.join()
f.await_admission(timeout=60)
# the vote wait doubles as a step barrier (compile/scheduling skew is
# absorbed at the vote, not accumulated) and heartbeats through it —
# a rank blocked on slower peers must not read as partitioned
mon = IntegrityMonitor(f.root, rank=member, world=f.world(),
                       interval=4, vote_timeout=30.0,
                       heartbeat=f.heartbeat)
verified = snapshot(net, step)   # step 0: init is trivially verified
s = 0
deadline = time.monotonic() + 180
while s < STEPS and time.monotonic() < deadline:
    try:
        f.on_step()
    except MembershipChange:
        try:
            f.ack()
            f.shard()
        except WorkerFailure:
            # transiently evicted (not quarantined — that rank died
            # below): rejoin at the next epoch
            f.join()
            f.await_admission(timeout=60)
        mon.set_world(f.world())
        continue
    step.step(nd.array(X), nd.array(Y))
    s += 1
    try:
        mon.on_committed_step(s, fp=step.fingerprint())
    except DataCorruption as e:
        if e.self_corrupt:
            # the vote named THIS rank: quarantine self (permanent) and
            # die loudly — the launcher must refuse the restart
            f.quarantine(member, reason=str(e)[:200], step=s)
            telemetry.flush(final=True)
            print("WORKER QUARANTINED", member, flush=True)
            sys.exit(3)
        # survivor: drop the corrupt rank from the vote cohort NOW (its
        # stale fingerprint file must not poison the replayed vote),
        # restore the last VERIFIED weights and replay from there
        mon.set_world([m for m in mon.world if m not in e.minority])
        for k, p in net.collect_params().items():
            p.set_data(nd.array(verified[k]))
        step.sync_from_net()
        s = e.verified_step
        continue
    if mon.verified_step == s:
        verified = snapshot(net, step)
telemetry.flush(final=True)
dump_final(net, step, str(member))
f.leave()
print("WORKER DONE", member, flush=True)
"""


# The serve tier's workload (ISSUE 8): a fixed-seed request storm
# against the serving runtime with every serving chaos knob armed in
# turn — reject_storm (admission backpressure + client resubmit), a
# hung decode (slow_decode_step -> watchdog -> classified engine
# restart) and NaN logits (nan_after -> NumericDivergence -> restart).
# Storm prompts share a per-storm template prefix so the shared-prefix
# index (ISSUE 12 — the tier runs with TPUMX_PREFIX_SHARING=1) is
# actually exercised under every fault, not just present.
# Hard assertions: ZERO lost requests (every submission eventually
# completes with its full token budget), a schema-valid black box per
# injected fault whose timeline correlates injection -> decision by
# shared (step, generation), catalog-valid serving metrics, and the
# post-storm allocator audit — with the prefix index dropped, every
# block refcount is back at zero (no reference leaks under restarts,
# preemption, or requeues).
SERVE_SCRIPT = """
import json
import os
import random
from tpu_mx import serving, telemetry, tracing
from tpu_mx.contrib import chaos
from tpu_mx.serving import AdmissionReject
from tpu_mx.telemetry import ATTRIBUTION_TOLERANCE as ATOL

D = os.environ["TPUMX_SERVE_DIR"]
SEED = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
SHARING = os.environ.get("TPUMX_PREFIX_SHARING", "0") not in ("", "0")
rng = random.Random(SEED)
model = serving.TinyLM(vocab_size=64, embed_dim=32, num_heads=2,
                       num_layers=2, seed=SEED % 997)


def storm(tag, fault, n_req=12, **srv_kw):
    tracing.reset()
    prefix = os.path.join(D, tag)
    srv = serving.Server(model, num_blocks=96, block_size=8, max_batch=4,
                         max_pending=64, max_tokens=100000, backoff=0.0,
                         blackbox=prefix,
                         # ISSUE 19: every storm runs with the durable
                         # committed-token journal armed — the journal
                         # write path must survive the same faults the
                         # data plane does (and the telemetry gate
                         # requires its counters nonzero)
                         journal=prefix + "-jr",
                         slo=serving.SLOMonitor(("itl_p99 < 30s",
                                                 "ttft_p99 < 30s"),
                                                windows=(5.0, 30.0)),
                         **srv_kw)
    # a 12-token storm template: every prompt shares its first full
    # 8-block, so prefix sharing (when armed) is hit by request #2 on
    template = [1 + rng.randrange(40) for _ in range(12)]
    todo = [(template + [1 + rng.randrange(40)
                         for _ in range(rng.randint(1, 5))],
             rng.randint(2, 8)) for _ in range(n_req)]
    reqs = []
    with chaos.enable(seed=SEED, **fault):
        for prompt, mnt in todo:
            while True:   # backpressure contract: a reject is a signal
                try:      # to drain and RESUBMIT, never a lost request
                    reqs.append(srv.submit(prompt, max_new_tokens=mnt))
                    break
                except AdmissionReject as e:
                    assert e.reason in ("reject_storm", "queue_full"), e
                    srv.run_until_idle()
        srv.run_until_idle()
    for (prompt, mnt), r in zip(todo, reqs):   # ZERO lost requests
        assert r.state == "done", (tag, r)
        assert len(r.tokens) == mnt, (tag, r, mnt)
        # the SLO engine's attribution invariant (ISSUE 11): the typed
        # phases must sum to the independently stamped wall clock within
        # telemetry.ATTRIBUTION_TOLERANCE (1 ms absolute floor for
        # sub-ms requests), restart-penalty phases included — a seam
        # that stops closing its interval, or double-counts one, breaks
        # this for every faulted request
        tl = r.timeline
        lat = r.finished_at - r.submitted_at
        assert abs(tl.total - lat) <= max(ATOL * lat, 1e-3), (
            tag, r.id, tl.total, lat, tl.phases)
        ttft_sum = sum(tl.ttft_breakdown.values())
        assert abs(ttft_sum - r.ttft) <= max(ATOL * r.ttft, 1e-3), (
            tag, r.id, ttft_sum, r.ttft, tl.ttft_breakdown)
    if srv.restarts:
        # every in-flight request the restart requeued must carry a
        # nonzero restart_penalty phase (the re-run is attributed, not
        # smeared into queue_wait)
        bounced = [r for r in reqs if r.timeline.requeues]
        assert bounced, tag
        assert all(r.timeline.phases.get("restart_penalty", 0) > 0
                   for r in bounced), (tag, bounced)
        # zero-regeneration receipt (ISSUE 19): recovery was paid with
        # replay prefills, not re-decoded catch-up steps
        replays = [e for e in tracing.snapshot()
                   if e["event"] == "serve.prefill"
                   and e["data"]["replayed"] > 0]
        assert replays, tag
        assert telemetry.get("serve.redecode_tokens") is None, tag
        if SHARING and tracing.stats()["dropped"] == 0:
            # satellite bugfix: the requeued storm requests share the
            # template — their replays must RIDE the rebuilt engine's
            # prefix index (prefix re-prefilled once, hit thereafter),
            # not re-prefill it once per request
            assert any(e["data"]["cached"] > 0 for e in replays), (
                tag, [(e["data"]["request"], e["data"]["cached"],
                       e["data"]["replayed"]) for e in replays])
    # the live monitor published its gauges and signal hook
    sig = srv.slo_signal
    assert sig is not None and not sig["breaching"], (tag, sig)
    assert srv.scheduler.slo_signal is sig, tag
    for name in ("itl_p99", "ttft_p99"):
        assert telemetry.get("serve.slo_estimate_seconds",
                             slo=name) is not None, (tag, name)
    # post-storm allocator audit (ISSUE 12): every sequence is done and
    # evicted; with the prefix index dropped, every block refcount must
    # be back at zero — restarts, preemptions and requeues may not leak
    # references.  When sharing is armed, the template prompts must have
    # actually HIT the index (the storm exercises sharing, not just
    # carries the knob).
    cache = srv.engine.cache
    if SHARING:
        st = cache.prefix_stats()
        assert st["hits"] > 0, (tag, st)
    # post-storm ledger audit (ISSUE 14), BEFORE the index drop: the
    # accounting identity — per block, attributed refs == refcount; per
    # tenant, amortized bytes sum EXACTLY to pool-used bytes — must
    # hold at the storm's end state (audit raises on any violation)
    cache.audit()
    cache.drop_prefix_cache()
    leftover = cache.allocator.refcounts()
    assert not leftover, (tag, leftover)
    assert cache.allocator.used == 0, (tag, cache.stats())
    # ... and AFTER the drop: zero residual attributed bytes
    rep = cache.audit()
    assert rep["used_blocks"] == 0 and not rep["tenants"], (tag, rep)
    # an end-of-run audit box: unlike the restart-time box it contains
    # the finished requests' serve.request_timeline events — what
    # tools/slo_report.py's worst-request section (and its offline
    # re-check of the attribution invariant) reads
    tracing.dump_blackbox(prefix + "-audit",
                          reason=f"serve {tag} slo audit")
    path = tracing.blackbox_path(prefix)
    if not os.path.exists(path):   # faults with no restart (reject
        tracing.dump_blackbox(prefix, reason=f"serve {tag} audit")
    box = json.load(open(path))
    tracing.validate_blackbox(box)
    return srv, box


def correlated(box, kind, *names):
    evs = box["events"]
    inj = [e for e in evs if e["event"] == "chaos.inject"
           and e["data"]["kind"] == kind]
    assert inj, (kind, sorted({e["event"] for e in evs}))
    key = (inj[0]["step"], inj[0]["generation"])
    got = [e["event"] for e in evs
           if (e["step"], e["generation"]) == key]
    for n in names:
        assert n in got, (kind, n, got)


srv, box = storm("sv-reject", dict(reject_storm=3))
assert srv.restarts == 0
correlated(box, "reject_storm", "serve.reject")

srv, box = storm("sv-hang", dict(slow_decode_step=5,
                                 slow_decode_seconds=30), deadline=1.0)
assert srv.restarts == 1, srv.restarts
correlated(box, "slow_decode_step", "serve.restart")

srv, box = storm("sv-nan", dict(nan_after=4))
assert srv.restarts == 1, srv.restarts
correlated(box, "nan", "serve.restart")

assert telemetry.get("serve.engine_restarts").value == 2
assert telemetry.get("serve.requests", state="requeued").value >= 1

# capacity pressure leg (ISSUE 14): a deliberately small pool forces
# genuine CacheExhausted (preemption) and, with sharing armed, prefix
# pressure evictions.  Every exhaustion must leave a forensic record
# naming 100% of live holders, the dump on disk must be schema-valid,
# and the ledger identity must hold through the whole ordeal.
tracing.reset()
cappfx = os.path.join(D, "sv-capacity")
srv = serving.Server(model, num_blocks=10, block_size=4, max_batch=4,
                     max_pending=64, max_tokens=100000, backoff=0.0,
                     blackbox=cappfx,
                     tenants={"t0": {"weight": 2.0}, "t1": {"weight": 1.0}})
caps = [srv.submit([1, 2, 3, 4, 5, 6, 7], max_new_tokens=8,
                   tenant=f"t{i % 2}") for i in range(6)]
srv.run_until_idle()
for r in caps:
    assert r.state == "done" and len(r.tokens) == 8, r
cache = srv.engine.cache
recs = cache.forensic_records()
n_exh = sum(1 for r in recs if r["kind"] == "exhaustion")
assert n_exh > 0, "the pressure leg must genuinely exhaust the pool"
exh_events = [e for e in tracing.snapshot()
              if e["event"] == "serve.capacity_exhausted"]
assert exh_events, "no serve.capacity_exhausted on the timeline"
if tracing.stats()["dropped"] == 0:   # ring intact: 1:1 with records
    assert len(exh_events) == n_exh, (len(exh_events), n_exh)
from tpu_mx.serving import validate_forensic_doc
cache.flush_forensics()   # disk dumps are rate-limited; audit wants 1:1
with open(cappfx + "-capacity.json") as f:
    capdoc = json.load(f)
validate_forensic_doc(capdoc)   # holders-complete + identity per record
assert len(capdoc["records"]) == len(recs), (len(capdoc["records"]),
                                             len(recs))
cache.audit()
cache.drop_prefix_cache()
assert not cache.allocator.refcounts()
rep = cache.audit()
assert rep["used_blocks"] == 0 and not rep["tenants"], rep
print("CAPACITY LEG OK", flush=True)

# the decode-path observables must record the arm this leg actually ran
# on: the black boxes carrying serve.decode_path for the restarted
# generations, with the ISSUE 16 fused/spec_window fields.  The fused
# arm runs attention INSIDE its one device program — decode_attention
# is never dispatched, so its counter is asserted only on the host arms
# and the fused legs assert the whole-step observables instead (the
# constant-3 host-crossing receipt included).
from tpu_mx.serving.speculative import resolve_spec_window
kind = ("paged" if os.environ.get("TPUMX_PAGED_DECODE", "0")
        not in ("", "0") else "dense")
FUSED = (kind != "dense" and
         os.environ.get("TPUMX_FUSED_DECODE", "0") not in ("", "0"))
SPECW = resolve_spec_window()
if FUSED:
    assert telemetry.get("serve.fused_steps") is not None
    assert telemetry.get("serve.decode_attention", kind=kind) is None
    # per-token crossings = 3 / tokens-emitted-that-step: the constant-3
    # numerator means the gauge can never exceed 3.0 (one sequence, one
    # token), and any host-resident re-entry (4*layers numerator) would
    # blow straight past it
    xing = telemetry.get("serve.host_crossings_per_token")
    assert xing is not None and 0.0 < xing.value <= 3.0, xing
else:
    assert telemetry.get("serve.decode_attention",
                         kind=kind) is not None, kind
if SPECW > 1:
    assert telemetry.get("serve.spec_drafted").value > 0
    ratio = telemetry.get("serve.spec_accept_ratio")
    assert ratio is not None and 0.0 <= ratio.value <= 1.0, ratio
paths = [e for e in box["events"] if e["event"] == "serve.decode_path"]
assert paths and all(e["data"]["path"] == kind for e in paths), (kind, paths)
assert all(e["data"]["fused"] is FUSED for e in paths), (FUSED, paths)
assert all(e["data"]["spec_window"] == SPECW for e in paths), (SPECW, paths)
telemetry.flush(final=True)
print("SERVE OK", flush=True)
"""

# Kernel-parity gate (ISSUE 9): a fixed trace decoded through the dense
# reference arm and through the FORCED Pallas kernel (interpret mode on
# CPU — the real kernel code path) must produce identical greedy token
# streams through the Server path, and the raw attention outputs must
# agree within the documented f32-stats tolerance (DIVERGENCES #27).
SERVE_PARITY_SCRIPT = """
import os
import numpy as np
from tpu_mx import serving
from tpu_mx.serving.attention import decode_attention

SEED = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
model = serving.TinyLM(vocab_size=64, embed_dim=32, num_heads=2,
                       num_layers=2, seed=SEED % 997)
prompts = [[5, 6, 7], [9, 2], [1] * 7]


def run(mode, fused="0", spec="0"):
    os.environ["TPUMX_PAGED_DECODE"] = mode
    os.environ["TPUMX_FUSED_DECODE"] = fused
    os.environ["TPUMX_SPECULATIVE"] = spec
    srv = serving.Server(model, num_blocks=64, max_batch=4)
    reqs = [srv.submit(p, max_new_tokens=6) for p in prompts]
    srv.run_until_idle()
    return [r.tokens for r in reqs]


dense = run("0")
kernel = run("kernel")
assert dense == kernel, (dense, kernel)

# ISSUE 16: the fused whole-step program and speculative decode are pure
# perf arms — every (decode mode, fused, spec) combination must emit the
# dense reference's exact greedy streams (greedy verification is
# lossless; the fused program imports the SAME weights)
for mode in ("0", "1", "kernel"):
    for fused in ("0", "1"):
        for spec in ("0", "1"):
            got = run(mode, fused, spec)
            assert got == dense, (mode, fused, spec, got, dense)

# raw-logits tolerance on a shared churned cache (both arms, same pool)
os.environ["TPUMX_PAGED_DECODE"] = "0"
eng = serving.EngineCore(model, block_size=4, num_blocks=32)
rng = np.random.RandomState(SEED % 2311)
for i, length in enumerate((6, 3, 9)):
    k = rng.rand(2, length, 2, 16).astype(np.float32)
    eng.cache.prefill(f"s{i}", k, k * 0.5)
eng.cache.free_sequence("s1")
k = rng.rand(2, 5, 2, 16).astype(np.float32)
eng.cache.prefill("s3", k, -k)
q = rng.rand(3, 2, 16).astype(np.float32)
ids = ["s0", "s2", "s3"]
want = decode_attention(q, eng.cache, ids, 1, kind="dense")
got = decode_attention(q, eng.cache, ids, 1, kind="paged-kernel")
drift = float(np.max(np.abs(got - want)))
assert drift <= 2e-5, drift
print(f"SERVE PARITY OK drift={drift:.2e}", flush=True)
"""

# Zero-regeneration recovery gate (ISSUE 19), stage 1: a victim process
# with the committed-token journal armed that the chaos layer kills with
# a REAL ``os._exit(137)`` mid-decode (TPUMX_CHAOS=kill9_at_decode_step
# is wired from the driver's env).  The driver asserts rc == 137; stage
# 2 (SERVE_RECOVERY_SCRIPT) then recovers from the journal this process
# left behind — a genuinely cross-process crash, not a simulated one.
SERVE_KILL9_CHILD = """
import os
from tpu_mx import serving

D = os.environ["TPUMX_SERVE_DIR"]
SEED = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
model = serving.TinyLM(vocab_size=64, embed_dim=32, num_heads=2,
                       num_layers=2, seed=SEED % 997)
srv = serving.Server(model, num_blocks=96, block_size=8, max_batch=4,
                     backoff=0.0, journal=os.path.join(D, "k9"))
for i, p in enumerate(([7, 8, 9], [7, 8, 10, 11], [3, 4])):
    srv.submit(p, max_new_tokens=48, request_id=f"r{i}")
srv.run_until_idle()   # TPUMX_CHAOS=kill9_at_decode_step=30 fires here
print("KILL9 DID NOT FIRE", flush=True)
"""

# Stage 2 of the recovery gate, a FRESH process: (1) resume the victim's
# streams from the fsync'd journal bit-identical to an uninterrupted
# run; (2) drain & hot handoff under live load with zero client-visible
# failures; (3) the A/B restart-penalty gate — on >=128-committed-token
# streams, prefill replay (ONE prefill per sequence) must beat the
# legacy prompt-replay arm (sequential re-decode of every committed
# token) by >= 3x on the worst request's restart_penalty phase.
SERVE_RECOVERY_SCRIPT = """
import os
from tpu_mx import serving, telemetry, tracing
from tpu_mx.contrib import chaos
from tpu_mx.serving import AdmissionReject
from tpu_mx.serving.journal import journal_path
from tpu_mx.serving.journal import load as journal_load

D = os.environ["TPUMX_SERVE_DIR"]
SEED = int(os.environ.get("TPUMX_CHAOS_SEED", "0"))
model = serving.TinyLM(vocab_size=64, embed_dim=32, num_heads=2,
                       num_layers=2, seed=SEED % 997)


def cval(name):
    rec = telemetry.get(name)
    return 0 if rec is None else rec.value


def reference(prompts, max_new):
    srv = serving.Server(model, num_blocks=96, block_size=8, max_batch=4,
                         backoff=0.0)
    reqs = [srv.submit(p, max_new_tokens=max_new) for p in prompts]
    srv.run_until_idle()
    return [list(r.tokens) for r in reqs]


# --- leg 1: cross-process kill -9 recovery ----------------------------
# The victim (SERVE_KILL9_CHILD, rc=137) left a journal in D.  Recovery
# must resume every stream from the fsync'd committed ledger with ONE
# prefill each — bit-identical to the uninterrupted run, zero tokens
# re-decoded, zero lost, the committed prefix untouched.
entries = journal_load(journal_path(os.path.join(D, "k9")))
assert len(entries) == 3, sorted(entries)
assert not any(e["fallback"] for e in entries.values()), entries
survivors = {rid: list(e["tokens"]) for rid, e in entries.items()}
assert any(survivors.values()), "the victim committed no work"
ref = reference(([7, 8, 9], [7, 8, 10, 11], [3, 4]), 48)
srv = serving.Server(model, num_blocks=96, block_size=8, max_batch=4,
                     backoff=0.0, journal=os.path.join(D, "k9"))
handles = srv.recover()
srv.run_until_idle()
for i in range(3):
    got = list(handles[f"r{i}"].tokens)
    assert got == ref[i], (i, got, ref[i])
    assert got[:len(survivors[f"r{i}"])] == survivors[f"r{i}"], i
assert telemetry.get("serve.redecode_tokens") is None
assert cval("serve.replay_requests") == sum(
    1 for t in survivors.values() if t)
print("KILL9 RECOVERY OK", flush=True)

# --- leg 2: planned maintenance under live load -----------------------
tracing.reset()
dprompts = ([11, 12, 13], [11, 12, 14], [5, 6])
dref = reference(dprompts, 12)
srv = serving.Server(model, num_blocks=96, block_size=8, max_batch=4,
                     backoff=0.0, journal=os.path.join(D, "drain"))
reqs = [srv.submit(p, max_new_tokens=12) for p in dprompts]
for _ in range(4):
    srv.step()          # live mid-decode state
n = srv.handoff()       # hot handoff onto a fresh engine generation
assert n == 3, n
assert srv.restarts == 0, srv.restarts
srv.drain()             # quiesce: finish every live stream
assert [list(r.tokens) for r in reqs] == dref   # bit-identical streams
assert all(r.state == "done" for r in reqs), reqs
try:
    srv.submit([1], max_new_tokens=2)
    raise AssertionError("a draining server accepted an admission")
except AdmissionReject as e:
    assert e.reason == "draining", e
srv.resume_admission()
late = srv.submit([1], max_new_tokens=2)
srv.run_until_idle()
assert late.state == "done", late
kinds = [e["data"]["kind"] for e in tracing.snapshot()
         if e["event"] == "serve.drain"]
assert kinds == ["handoff", "drain"], kinds
print("DRAIN LEG OK", flush=True)

# --- leg 3: the zero-regeneration payoff, CI-gated --------------------
# Warm the replay-prefill sequence lengths OUTSIDE the timed phase: the
# replay prefill re-feeds prompt+committed (~135 tokens) in one call, a
# length nothing else in this process has compiled — without the warmup
# the gate would time XLA compilation, not recovery work.
for L in (133, 134, 135, 136, 137):
    reference([[1 + i % 40 for i in range(L)]], 1)


def deep_storm(tag, fault, replay, **srv_kw):
    # a fault deep into decode: every stream has >= 128 committed
    # tokens when it fires, the worst case for prompt replay
    srv = serving.Server(model, num_blocks=96, block_size=8, max_batch=4,
                         backoff=0.0, replay=replay,
                         journal=os.path.join(D, tag), **srv_kw)
    with chaos.enable(seed=SEED, **fault):
        reqs = [srv.submit(p, max_new_tokens=140)
                for p in ([21, 22, 23], [21, 22, 24])]
        srv.run_until_idle()
    assert srv.restarts == 1, (tag, srv.restarts)
    for r in reqs:
        assert r.state == "done" and len(r.tokens) == 140, (tag, r)
        assert r.timeline.requeues == 1, (tag, r.id)
    return max(r.timeline.phases["restart_penalty"] for r in reqs)


# receipt: a HANG storm (watchdog restart) 132 committed tokens deep —
# recovery is exactly one replay prefill per sequence, zero re-decoded
before_rq, before_rt = cval("serve.replay_requests"), cval(
    "serve.replay_tokens")
deep_storm("hang-replay", dict(slow_decode_step=132,
                               slow_decode_seconds=30),
           replay=True, deadline=2.0)
assert cval("serve.replay_requests") - before_rq == 2   # ONE prefill each
replayed = cval("serve.replay_tokens") - before_rt
assert replayed >= 2 * 128, replayed   # >= 128 committed per stream
assert cval("serve.redecode_tokens") == 0

# the >= 3x gate runs on the NaN fault: the health gate detects it at
# decode-check speed (sub-ms), so restart_penalty measures RECOVERY
# work, not fault-detection latency — on the hang arm above both
# recovery strategies pay the same 2s watchdog wait, which would mask
# the replay win
before_rq, before_rt = cval("serve.replay_requests"), cval(
    "serve.replay_tokens")
pen_replay = deep_storm("ab-replay", dict(nan_after=132), replay=True)
assert cval("serve.replay_requests") - before_rq == 2
assert cval("serve.replay_tokens") - before_rt >= 2 * 128
assert cval("serve.redecode_tokens") == 0

before_rq, before_rd = cval("serve.replay_requests"), cval(
    "serve.redecode_tokens")
pen_legacy = deep_storm("ab-legacy", dict(nan_after=132), replay=False)
assert cval("serve.replay_requests") - before_rq == 0
redecoded = cval("serve.redecode_tokens") - before_rd
assert redecoded >= 2 * 128, redecoded
assert pen_legacy >= 3.0 * pen_replay, (pen_legacy, pen_replay)
print("AB GATE OK replay=%.1fms legacy=%.1fms ratio=%.1fx"
      % (pen_replay * 1e3, pen_legacy * 1e3, pen_legacy / pen_replay),
      flush=True)
telemetry.flush(final=True)
print("RECOVER OK", flush=True)
"""

SERVE_REQUIRED = ("serve", "chaos.injections")

# per-box markers the RENDERED report (tools/blackbox_report.py, run
# under a poisoned jax import) must contain: the injection and the
# decision in prose
SERVE_BOX_EXPECT = {
    "sv-reject": ("chaos reject_storm injected", "admission rejected"),
    "sv-hang": ("chaos slow_decode_step injected", "engine restart #"),
    "sv-nan": ("chaos nan injected", "engine restart #"),
}


def _serve_storm_leg(mode, spec="0", fused="0"):
    """One full chaos-storm pass (the three faults) with the decode arm
    pinned to `mode` ("0" = dense-gather reference, "1" = paged:
    device-resident pool + block-table program) and shared-prefix KV
    reuse ENABLED (ISSUE 12: the self-healing contract must hold with
    sharing on — the storm script's post-storm allocator audit asserts
    every refcount returns to zero), then telemetry validation and
    jax-less black-box rendering.  ISSUE 16 adds `spec`
    (TPUMX_SPECULATIVE) and `fused` (TPUMX_FUSED_DECODE): the fused
    whole-step arm and speculative windows must survive the same storms
    with zero lost requests and a clean post-storm allocator audit
    (fused silently downgrades to the host arm on mode "0" — the script
    recomputes the effective arm and asserts the matching observables)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tag_mode = "dense" if mode in ("", "0") else "paged"
    if spec not in ("", "0"):
        tag_mode += "+spec"
    if fused not in ("", "0"):
        tag_mode += "+fused"
    with tempfile.TemporaryDirectory() as d:
        jsonl = os.path.join(d, "telemetry.jsonl")
        env = dict(os.environ, TPUMX_TELEMETRY=jsonl, JAX_PLATFORMS="cpu",
                   TPUMX_CHAOS_SEED="20260804", TPUMX_SERVE_DIR=d,
                   TPUMX_PAGED_DECODE=mode, TPUMX_PREFIX_SHARING="1",
                   TPUMX_SPECULATIVE=spec, TPUMX_FUSED_DECODE=fused)
        env.pop("TPUMX_CHAOS", None)    # the script arms its own faults
        env.pop("TPUMX_TRACING", None)  # the black boxes need the recorder
        try:
            run = subprocess.run([sys.executable, "-c", SERVE_SCRIPT],
                                 env=env, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: request storm timed out: {e}")
            return 1
        if run.returncode != 0 or "SERVE OK" not in (run.stdout or ""):
            print(f"  serve[{tag_mode}]: request storm failed "
                  f"(rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-4000:]}")
            return run.returncode or 1
        try:
            val = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "telemetry_report.py"),
                 jsonl, "--validate", "--require",
                 ",".join(SERVE_REQUIRED)],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: telemetry validation timed out: "
                  f"{e}")
            return 1
        if val.returncode != 0:
            print(f"  serve[{tag_mode}]: telemetry validation failed "
                  f"(rc={val.returncode}):\n"
                  f"{((val.stdout or '') + (val.stderr or ''))[-3000:]}")
            return val.returncode or 1
        report = os.path.join(repo, "tools", "blackbox_report.py")
        for tag, expect in SERVE_BOX_EXPECT.items():
            box = os.path.join(d, f"{tag}-blackbox.json")
            code = ("import sys, runpy; "
                    "sys.modules['jax'] = None; "
                    "sys.modules['tpu_mx'] = None; "
                    f"sys.argv = ['blackbox_report.py', {box!r}, "
                    "'--validate']; "
                    f"runpy.run_path({report!r}, run_name='__main__')")
            try:
                ren = subprocess.run([sys.executable, "-c", code],
                                     capture_output=True, text=True,
                                     timeout=120)
            except subprocess.TimeoutExpired as e:
                print(f"  serve[{tag_mode}]: blackbox report timed out "
                      f"on {tag}: {e}")
                return 1
            out = (ren.stdout or "") + (ren.stderr or "")
            if ren.returncode != 0:
                print(f"  serve[{tag_mode}]: blackbox report failed on "
                      f"{tag} (rc={ren.returncode}):\n{out[-3000:]}")
                return 1
            missing = [m for m in expect if m not in out]
            if missing:
                print(f"  serve[{tag_mode}]: blackbox report for {tag} "
                      f"is missing timeline markers {missing}:"
                      f"\n{out[-3000:]}")
                return 1
        # the SLO ops surface, under the same poisoned-jax discipline:
        # schema-gate the storm's telemetry (window sub-objects
        # included) plus the end-of-run audit box, whose request
        # timelines slo_report re-checks against the 5% attribution
        # invariant offline — and whose worst-request section must
        # actually render recorded timelines
        slo_tool = os.path.join(repo, "tools", "slo_report.py")
        audit = os.path.join(d, "sv-nan-audit-blackbox.json")
        code = ("import sys, runpy; "
                "sys.modules['jax'] = None; "
                "sys.modules['tpu_mx'] = None; "
                f"sys.argv = ['slo_report.py', {jsonl!r}, "
                f"'--box', {audit!r}, '--validate']; "
                f"runpy.run_path({slo_tool!r}, run_name='__main__')")
        try:
            slo = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: slo_report timed out: {e}")
            return 1
        out = (slo.stdout or "") + (slo.stderr or "")
        if slo.returncode != 0:
            print(f"  serve[{tag_mode}]: slo_report failed "
                  f"(rc={slo.returncode}):\n{out[-3000:]}")
            return 1
        # "serving.SLOMonitor state" appears only in the ARMED gauge
        # rendering — the none-armed fallback line also says "Live
        # monitor gauges", which would let missing serve.slo_* series
        # slip through a looser marker
        missing = [m for m in ("SLO targets", "Worst requests by latency",
                               "serving.SLOMonitor state",
                               "Restart recovery",
                               "Per-tenant SLO state")
                   if m not in out]
        if missing or "top 5 of 0 recorded" in out:
            print(f"  serve[{tag_mode}]: slo_report output is missing "
                  f"sections {missing or ['request timelines']}:"
                  f"\n{out[-3000:]}")
            return 1
        # the capacity ops surface (ISSUE 14), same poisoned-jax
        # discipline: schema-gate the storm's telemetry (the per-tenant
        # pool_bytes identity re-checked offline per snapshot) plus the
        # pressure leg's forensic dump, whose records must name 100% of
        # the live holders and satisfy the identity record-by-record
        cap_tool = os.path.join(repo, "tools", "capacity_report.py")
        capjson = os.path.join(d, "sv-capacity-capacity.json")
        code = ("import sys, runpy; "
                "sys.modules['jax'] = None; "
                "sys.modules['tpu_mx'] = None; "
                f"sys.argv = ['capacity_report.py', {jsonl!r}, "
                f"'--forensics', {capjson!r}, '--validate']; "
                f"runpy.run_path({cap_tool!r}, run_name='__main__')")
        try:
            cap = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: capacity_report timed out: {e}")
            return 1
        out = (cap.stdout or "") + (cap.stderr or "")
        if cap.returncode != 0:
            print(f"  serve[{tag_mode}]: capacity_report failed "
                  f"(rc={cap.returncode}):\n{out[-3000:]}")
            return 1
        missing = [m for m in ("Ledger timeline",
                               "Per-tenant pool attribution",
                               "Exhaustion forensics", "schema OK")
                   if m not in out]
        if missing or "0 forensic record(s)" in out:
            print(f"  serve[{tag_mode}]: capacity_report output is "
                  f"missing sections {missing or ['forensic records']}:"
                  f"\n{out[-3000:]}")
            return 1
    return 0


def _serve_recovery_leg(mode):
    """The zero-regeneration recovery gate (ISSUE 19), per decode mode:
    stage 1 runs SERVE_KILL9_CHILD with the journal armed and chaos
    wired to ``os._exit(137)`` mid-decode (the driver asserts the 137);
    stage 2 runs SERVE_RECOVERY_SCRIPT in a FRESH process — journal
    recovery bit-identical to the uninterrupted run, drain & hot
    handoff under live load, and the A/B gate (prefill replay beats the
    legacy prompt-replay arm >= 3x on restart_penalty for streams with
    >= 128 committed tokens); then the jax-less slo_report rendering of
    the restart-recovery section from the leg's telemetry."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tag_mode = "dense" if mode in ("", "0") else "paged"
    with tempfile.TemporaryDirectory() as d:
        jsonl = os.path.join(d, "telemetry.jsonl")
        base = dict(os.environ, JAX_PLATFORMS="cpu",
                    TPUMX_CHAOS_SEED="20260807", TPUMX_SERVE_DIR=d,
                    TPUMX_PAGED_DECODE=mode, TPUMX_PREFIX_SHARING="1",
                    TPUMX_SPECULATIVE="0", TPUMX_FUSED_DECODE="0")
        for k in ("TPUMX_CHAOS", "TPUMX_TRACING", "TPUMX_TELEMETRY",
                  "TPUMX_PREFILL_REPLAY"):
            base.pop(k, None)
        kenv = dict(base, TPUMX_CHAOS="kill9_at_decode_step=30")
        try:
            kid = subprocess.run([sys.executable, "-c", SERVE_KILL9_CHILD],
                                 env=kenv, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: kill -9 victim timed out: {e}")
            return 1
        if kid.returncode != 137 or "KILL9 DID NOT FIRE" in (kid.stdout
                                                             or ""):
            print(f"  serve[{tag_mode}]: kill -9 victim exited "
                  f"rc={kid.returncode}, wanted 137:\n"
                  f"{((kid.stdout or '') + (kid.stderr or ''))[-3000:]}")
            return 1
        renv = dict(base, TPUMX_TELEMETRY=jsonl)
        try:
            rec = subprocess.run([sys.executable, "-c",
                                  SERVE_RECOVERY_SCRIPT],
                                 env=renv, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: recovery leg timed out: {e}")
            return 1
        if rec.returncode != 0 or "RECOVER OK" not in (rec.stdout or ""):
            print(f"  serve[{tag_mode}]: recovery leg failed "
                  f"(rc={rec.returncode}):\n"
                  f"{((rec.stdout or '') + (rec.stderr or ''))[-4000:]}")
            return rec.returncode or 1
        # the recovery ops surface, under the poisoned-jax discipline:
        # slo_report must render the restart-recovery section with the
        # leg's replay/journal receipts (and schema-gate the telemetry)
        slo_tool = os.path.join(repo, "tools", "slo_report.py")
        code = ("import sys, runpy; "
                "sys.modules['jax'] = None; "
                "sys.modules['tpu_mx'] = None; "
                f"sys.argv = ['slo_report.py', {jsonl!r}, '--validate']; "
                f"runpy.run_path({slo_tool!r}, run_name='__main__')")
        try:
            slo = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  serve[{tag_mode}]: recovery slo_report timed out: "
                  f"{e}")
            return 1
        out = (slo.stdout or "") + (slo.stderr or "")
        if slo.returncode != 0:
            print(f"  serve[{tag_mode}]: recovery slo_report failed "
                  f"(rc={slo.returncode}):\n{out[-3000:]}")
            return 1
        missing = [m for m in ("Restart recovery", "replayed sequences",
                               "replayed tokens", "journal")
                   if m not in out]
        if missing:
            print(f"  serve[{tag_mode}]: recovery slo_report output is "
                  f"missing sections {missing}:\n{out[-3000:]}")
            return 1
        ab = [ln for ln in (rec.stdout or "").splitlines()
              if ln.startswith("AB GATE OK")]
        print(f"  serve[{tag_mode}]: recovery gate OK "
              f"({ab[0] if ab else 'RECOVER OK'})")
    return 0


def serve_tier():
    """Run the chaos request storm against the serving runtime in BOTH
    decode modes (dense-gather reference and TPUMX_PAGED_DECODE=1 —
    ISSUE 9: the self-healing contract is data-plane-independent), plus
    the ISSUE 16 legs (fused whole-step arm + TPUMX_SPECULATIVE=1 in
    both decode modes — on dense the fused knob downgrades to the host
    arm, which is itself part of the contract), then the kernel-parity
    gate: the forced Pallas kernel (interpret on CPU) must reproduce
    the dense arm's greedy tokens exactly — fused on/off and
    speculative on/off included — and its logits within the documented
    tolerance.  ISSUE 19 adds the zero-regeneration recovery gate per
    decode mode: a real cross-process kill -9 recovered from the
    committed-token journal, drain & hot handoff under live load, and
    the CI-gated >= 3x restart_penalty win of prefill replay over the
    legacy prompt-replay arm."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for mode, spec, fused in (("0", "0", "0"), ("1", "0", "0"),
                              ("0", "1", "1"), ("1", "1", "1")):
        rc = _serve_storm_leg(mode, spec, fused)
        if rc != 0:
            return rc
    # the ISSUE 19 recovery gate (kill -9 + journal recovery, drain &
    # handoff, replay-vs-redecode A/B), on both decode data planes
    for mode in ("0", "1"):
        rc = _serve_recovery_leg(mode)
        if rc != 0:
            return rc
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TPUMX_CHAOS_SEED="20260804")
    env.pop("TPUMX_CHAOS", None)
    try:
        par = subprocess.run([sys.executable, "-c", SERVE_PARITY_SCRIPT],
                             env=env, cwd=repo, capture_output=True,
                             text=True, timeout=600)
    except subprocess.TimeoutExpired as e:
        print(f"  serve: kernel-parity gate timed out: {e}")
        return 1
    if par.returncode != 0 or "SERVE PARITY OK" not in (par.stdout or ""):
        print(f"  serve: kernel-parity gate failed "
              f"(rc={par.returncode}):\n"
              f"{((par.stdout or '') + (par.stderr or ''))[-4000:]}")
        return par.returncode or 1
    print(f"  {(par.stdout or '').strip().splitlines()[-1]}")
    return 0


def soak_tier():
    """Run the supervised chaos-soak training job with a FIXED chaos seed
    and bounded wall-clock, then validate its telemetry (the supervisor
    metrics must all be nonzero — recovery paths taken, not assumed)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as d:
        jsonl = os.path.join(d, "telemetry.jsonl")
        env = dict(os.environ, TPUMX_TELEMETRY=jsonl, JAX_PLATFORMS="cpu",
                   TPUMX_CHAOS_SEED="20260804")
        env.pop("TPUMX_CHAOS", None)  # the script arms its own schedule
        env.pop("TPUMX_TRACING", None)  # the blackbox leg needs the recorder
        try:
            run = subprocess.run([sys.executable, "-c", SOAK_SCRIPT],
                                 env=env, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: supervised run timed out: {e}")
            return 1
        if run.returncode != 0 or "SOAK OK" not in (run.stdout or ""):
            print(f"  soak: supervised run failed (rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-4000:]}")
            return run.returncode or 1
        try:
            val = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "telemetry_report.py"),
                 jsonl, "--validate", "--require", ",".join(SOAK_REQUIRED)],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: telemetry validation timed out: {e}")
            return 1
        if val.returncode != 0:
            print(f"  soak: telemetry validation failed "
                  f"(rc={val.returncode}):\n"
                  f"{((val.stdout or '') + (val.stderr or ''))[-3000:]}")
            return val.returncode or 1
    # membership-churn leg (ISSUE 17): seeded partition -> reshard down,
    # heal -> rejoin -> reshard up, SIGTERM preempt survived — with the
    # global sample-id ledger gated against the uninterrupted oracle
    with tempfile.TemporaryDirectory() as d:
        jsonl = os.path.join(d, "telemetry.jsonl")
        env = dict(os.environ, TPUMX_TELEMETRY=jsonl, JAX_PLATFORMS="cpu",
                   TPUMX_CHAOS_SEED="20260804",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2")
        env.pop("TPUMX_CHAOS", None)  # the script arms its own schedule
        env.pop("TPUMX_TRACING", None)
        try:
            run = subprocess.run([sys.executable, "-c", FLEET_SCRIPT],
                                 env=env, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: fleet churn run timed out: {e}")
            return 1
        if run.returncode != 0 or "FLEET OK" not in (run.stdout or ""):
            print(f"  soak: fleet churn run failed (rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-4000:]}")
            return run.returncode or 1
        try:
            val = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "telemetry_report.py"),
                 jsonl, "--validate", "--require",
                 ",".join(FLEET_REQUIRED)],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: fleet telemetry validation timed out: {e}")
            return 1
        if val.returncode != 0:
            print(f"  soak: fleet telemetry validation failed "
                  f"(rc={val.returncode}):\n"
                  f"{((val.stdout or '') + (val.stderr or ''))[-3000:]}")
            return val.returncode or 1
    # straggler sub-leg (ISSUE 18): the injected straggler must be
    # named, with its dominant phase, under BOTH churn shapes
    for scenario in ("preempt", "partition"):
        rc = _straggler_leg(repo, scenario)
        if rc:
            return rc
    # SDC storm sub-leg (ISSUE 20): an injected parameter bit-flip must
    # be voted out, quarantined, never re-admitted — and the survivors'
    # rollback must cost ZERO correctness (bit-equal to uninjected)
    return _sdc_leg(repo)


def _straggler_leg(repo, scenario):
    """One supervised 2-worker fleet with rank 1 chaos-slowed, churned by
    ``scenario`` ("preempt": SIGTERM rank 0 mid-step -> evict -> restart
    -> rejoin; "partition": rank 1's beats suppressed -> lease expiry ->
    evict -> heal -> rejoin).  Gates the whole observability plane on
    the resulting artifacts."""
    with tempfile.TemporaryDirectory() as d:
        fleet_dir = os.path.join(d, "fleet")
        ctl_jsonl = os.path.join(d, "controller.jsonl")
        worker = os.path.join(d, "worker.py")
        with open(worker, "w") as f:
            f.write(STRAGGLER_WORKER)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TPUMX_TELEMETRY=ctl_jsonl, TPUMX_REPO=repo,
                   TPUMX_CI_DIR=d, TPUMX_CI_SCENARIO=scenario,
                   TPUMX_CI_STEPS="24")
        env.pop("TPUMX_CHAOS", None)   # scenario wiring below only
        env.pop("TPUMX_TRACING", None)
        argv = [sys.executable, os.path.join(repo, "tools", "launch.py"),
                "--supervise", "-n", "2", "--fleet-dir", fleet_dir,
                "--max-restarts", "2", "--backoff", "1.0",
                "--lease", "2.0", "--join-timeout", "60"]
        if scenario == "preempt":
            # the env-wired shape: rank 1 straggles all run, rank 0 is
            # SIGTERMed mid-step and comes back chaos-stripped
            argv += ["--env", "TPUMX_CHAOS=slow_worker_rank=1,"
                             "slow_worker_seconds=0.25,"
                             "preempt_worker_at_step=6,preempt_rank=0"]
        argv += [sys.executable, worker]
        try:
            run = subprocess.run(argv, env=env, cwd=repo,
                                 capture_output=True, text=True,
                                 timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: straggler/{scenario} run timed out: {e}")
            return 1
        if run.returncode != 0:
            print(f"  soak: straggler/{scenario} supervised run failed "
                  f"(rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-4000:]}")
            return run.returncode or 1
        box = os.path.join(fleet_dir, "fleet-blackbox.json")
        try:
            with open(box, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"  soak: straggler/{scenario}: no readable fleet "
                  f"black box at {box}: {e}")
            return 1
        sig = (doc.get("fleet") or {}).get("straggler_signal") or {}
        if not (sig.get("straggling") and sig.get("rank") == 1
                and sig.get("dominant_phase") == "data_wait"):
            print(f"  soak: straggler/{scenario}: detector did not name "
                  f"the injected rank/phase (signal={sig})")
            return 1
        skews = [c.get("skew_seconds", 0.0)
                 for c in (doc.get("fleet") or {}).get("skew_timeline", [])]
        if not skews or max(skews) <= 0.0:
            print(f"  soak: straggler/{scenario}: skew never moved "
                  f"(timeline={skews[:8]})")
            return 1
        # the report tool must work — and name rank 1 + the phase — on a
        # machine with NO accelerator stack (poisoned jax/tpu_mx)
        report = os.path.join(repo, "tools", "fleet_report.py")
        poison = ("import sys, runpy; sys.modules['jax'] = None; "
                  "sys.modules['tpu_mx'] = None; "
                  f"sys.argv = ['fleet_report', {box!r}, '--validate']; "
                  f"runpy.run_path({report!r}, run_name='__main__')")
        try:
            rep = subprocess.run([sys.executable, "-c", poison],
                                 capture_output=True, text=True,
                                 timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: straggler/{scenario}: fleet_report timed "
                  f"out: {e}")
            return 1
        out = rep.stdout or ""
        if rep.returncode != 0 or "rank 1" not in out \
                or "data_wait" not in out:
            print(f"  soak: straggler/{scenario}: fleet_report "
                  f"--validate failed (rc={rep.returncode}):\n"
                  f"{(out + (rep.stderr or ''))[-3000:]}")
            return rep.returncode or 1
        # aggregation identity across the controller + worker registries
        files = [ctl_jsonl] + [os.path.join(d, f"worker-{r}.jsonl")
                               for r in (0, 1)]
        missing = [p for p in files if not os.path.exists(p)]
        if missing:
            print(f"  soak: straggler/{scenario}: missing telemetry "
                  f"file(s): {missing}")
            return 1
        try:
            val = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "telemetry_report.py"),
                 "--merge", *files, "--validate",
                 "--require", "fleet_obs"],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: straggler/{scenario}: merged validation "
                  f"timed out: {e}")
            return 1
        if val.returncode != 0:
            print(f"  soak: straggler/{scenario}: merged telemetry "
                  f"validation failed (rc={val.returncode}):\n"
                  f"{((val.stdout or '') + (val.stderr or ''))[-3000:]}")
            return val.returncode or 1
        print(f"  soak: straggler/{scenario}: rank 1/data_wait "
              f"attributed, max skew {max(skews):.3f}s, merged "
              "identity holds")
    return 0


def _sdc_leg(repo):
    """One supervised 3-worker fleet of identical replicas with a seeded
    parameter bit-flip injected into rank 1's committed weights.  Gates
    the whole SDC defense plane: vote -> minority attribution ->
    self-quarantine -> launcher restart refusal -> survivor rollback to
    the last verified weights, bit-equal to an uninjected run."""
    import numpy as np
    with tempfile.TemporaryDirectory() as d:
        fleet_dir = os.path.join(d, "fleet")
        ctl_jsonl = os.path.join(d, "controller.jsonl")
        worker = os.path.join(d, "worker.py")
        with open(worker, "w") as f:
            f.write(SDC_WORKER)
        # the uninjected oracle first: same script, same seed, same
        # grid — no fleet, no integrity plane, no chaos
        base_env = dict(os.environ, JAX_PLATFORMS="cpu", TPUMX_REPO=repo,
                        TPUMX_CI_DIR=d, TPUMX_CI_BASELINE="1",
                        TPUMX_CI_STEPS="16")
        for k in ("TPUMX_CHAOS", "TPUMX_TRACING", "TPUMX_TELEMETRY"):
            base_env.pop(k, None)
        try:
            run = subprocess.run([sys.executable, worker], env=base_env,
                                 cwd=repo, capture_output=True, text=True,
                                 timeout=300)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: sdc baseline timed out: {e}")
            return 1
        if run.returncode != 0:
            print(f"  soak: sdc baseline run failed "
                  f"(rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-4000:]}")
            return run.returncode or 1
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TPUMX_TELEMETRY=ctl_jsonl, TPUMX_REPO=repo,
                   TPUMX_CI_DIR=d, TPUMX_CI_STEPS="16")
        for k in ("TPUMX_CHAOS", "TPUMX_TRACING", "TPUMX_CI_BASELINE"):
            env.pop(k, None)
        argv = [sys.executable, os.path.join(repo, "tools", "launch.py"),
                "--supervise", "-n", "3", "--fleet-dir", fleet_dir,
                "--max-restarts", "2", "--backoff", "1.0",
                "--lease", "4.0", "--join-timeout", "60",
                "--min-workers", "1",
                # the flip lands AFTER commit 6 on rank 1 only — the
                # step-8 vote is the first to see the divergence
                "--env", "TPUMX_CHAOS=bitflip_param_at_step=6,"
                         "bitflip_rank=1",
                sys.executable, worker]
        try:
            run = subprocess.run(argv, env=env, cwd=repo,
                                 capture_output=True, text=True,
                                 timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: sdc supervised run timed out: {e}")
            return 1
        out = (run.stdout or "") + (run.stderr or "")
        # rc 1 is EXPECTED: a quarantine is a degraded outcome and
        # supervise surfaces any nonzero worker exit as a failed launch
        if run.returncode not in (0, 1):
            print(f"  soak: sdc supervised run died "
                  f"(rc={run.returncode}):\n{out[-4000:]}")
            return run.returncode or 1
        if "WORKER QUARANTINED 1" not in out:
            print(f"  soak: sdc: rank 1 never self-quarantined:\n"
                  f"{out[-4000:]}")
            return 1
        if "WORKER DONE 0" not in out or "WORKER DONE 2" not in out:
            print(f"  soak: sdc: a survivor did not finish:\n"
                  f"{out[-4000:]}")
            return 1
        if "worker 1 quarantined" not in out:
            print(f"  soak: sdc: launcher never refused the restart:\n"
                  f"{out[-4000:]}")
            return 1
        if "worker 1 exited 3; restart" in out:
            print(f"  soak: sdc: launcher RESPAWNED a quarantined "
                  f"rank:\n{out[-4000:]}")
            return 1
        qrec = os.path.join(fleet_dir, "quarantine", "1.json")
        if not os.path.exists(qrec):
            print(f"  soak: sdc: no quarantine record at {qrec}")
            return 1
        # zero-correctness-cost rollback: both survivors' final weights
        # bit-equal to the uninjected fixed-seed run
        base = np.load(os.path.join(d, "final-baseline.npz"))
        for rank in (0, 2):
            fin = np.load(os.path.join(d, f"final-{rank}.npz"))
            for k in base.files:
                a, b = base[k], fin[k]
                if a.dtype != b.dtype or a.shape != b.shape \
                        or a.tobytes() != b.tobytes():
                    print(f"  soak: sdc: rank {rank} final weights "
                          f"diverge from the uninjected run at {k!r}")
                    return 1
        # the black box must carry the corruption verdict, and the
        # report tool must validate it on a machine with NO accelerator
        # stack (poisoned jax/tpu_mx)
        box = os.path.join(fleet_dir, "fleet-blackbox.json")
        try:
            with open(box, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"  soak: sdc: no readable fleet black box at "
                  f"{box}: {e}")
            return 1
        cv = (((doc.get("fleet") or {}).get("corruption") or {})
              .get("verdict") or {})
        if cv.get("clean") is not False or cv.get("quarantined") != [1] \
                or cv.get("suspected") != [1] \
                or not cv.get("mismatch_steps"):
            print(f"  soak: sdc: black box corruption verdict wrong: "
                  f"{cv}")
            return 1
        report = os.path.join(repo, "tools", "fleet_report.py")
        poison = ("import sys, runpy; sys.modules['jax'] = None; "
                  "sys.modules['tpu_mx'] = None; "
                  f"sys.argv = ['fleet_report', {box!r}, '--validate']; "
                  f"runpy.run_path({report!r}, run_name='__main__')")
        try:
            rep = subprocess.run([sys.executable, "-c", poison],
                                 capture_output=True, text=True,
                                 timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: sdc: fleet_report timed out: {e}")
            return 1
        if rep.returncode != 0 or "QUARANTINED" not in (rep.stdout or ""):
            print(f"  soak: sdc: fleet_report --validate failed "
                  f"(rc={rep.returncode}):\n"
                  f"{((rep.stdout or '') + (rep.stderr or ''))[-3000:]}")
            return rep.returncode or 1
        # merged telemetry: fingerprints published, votes held, the
        # injected flip counted as a mismatch, the corrupt rank counted
        # as quarantined
        files = [ctl_jsonl] + [os.path.join(d, f"worker-{r}.jsonl")
                               for r in (0, 1, 2)]
        missing = [p for p in files if not os.path.exists(p)]
        if missing:
            print(f"  soak: sdc: missing telemetry file(s): {missing}")
            return 1
        try:
            val = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "telemetry_report.py"),
                 "--merge", *files, "--validate",
                 "--require", "integrity"],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  soak: sdc: merged validation timed out: {e}")
            return 1
        if val.returncode != 0:
            print(f"  soak: sdc: merged telemetry validation failed "
                  f"(rc={val.returncode}):\n"
                  f"{((val.stdout or '') + (val.stderr or ''))[-3000:]}")
            return val.returncode or 1
        print("  soak: sdc: rank 1 voted out + quarantined, restart "
              "refused, survivors bit-equal to uninjected run, "
              "corruption verdict valid")
    return 0


def obs_tier():
    """Run the instrumented train loop with TPUMX_TELEMETRY set, then
    validate the emitted JSONL (schema + metric-name catalog + required
    nonzero metrics).  Returns a process-style rc (0 = green)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as d:
        jsonl = os.path.join(d, "telemetry.jsonl")
        env = dict(os.environ, TPUMX_TELEMETRY=jsonl, JAX_PLATFORMS="cpu")
        env.pop("TPUMX_CHAOS", None)  # a chaos-armed env would tear the run
        # TPUMX_FUSION=0 would force the bulk() blocks eager and zero the
        # required fusion.flushes (same scrub bench.py's fusion leg does)
        env.pop("TPUMX_FUSION", None)
        try:
            run = subprocess.run([sys.executable, "-c", OBS_SCRIPT],
                                 env=env, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  obs: train loop timed out: {e}")
            return 1
        if run.returncode != 0:
            print(f"  obs: train loop failed (rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-3000:]}")
            return run.returncode or 1
        try:
            val = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "telemetry_report.py"),
                 jsonl, "--validate", "--require", ",".join(OBS_REQUIRED)],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  obs: telemetry validation timed out: {e}")
            return 1
        out = (val.stdout or "") + (val.stderr or "")
        if val.returncode != 0:
            print(f"  obs: telemetry validation failed "
                  f"(rc={val.returncode}):\n{out[-3000:]}")
            return val.returncode or 1
        # the SLO ops surface must schema-gate the same snapshot (rc
        # 0/1/2 contract like blackbox_report): window sub-objects are
        # part of the record schema, and a training-only file must
        # render cleanly (no serving data is "no data", not an error)
        try:
            slo = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "slo_report.py"),
                 jsonl, "--validate"],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  obs: slo_report validation timed out: {e}")
            return 1
        if slo.returncode != 0:
            print(f"  obs: slo_report validation failed "
                  f"(rc={slo.returncode}):\n"
                  f"{((slo.stdout or '') + (slo.stderr or ''))[-3000:]}")
            return slo.returncode or 1
        # capacity_report must hold to the same rc contract on a
        # training-only snapshot: no serving data renders as "no data",
        # never as an error, and the training-side twins (per-shape
        # compiles, checkpoint bytes, host RSS) validate in catalog
        try:
            cap = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "capacity_report.py"),
                 jsonl, "--validate"],
                capture_output=True, text=True, timeout=120)
        except subprocess.TimeoutExpired as e:
            print(f"  obs: capacity_report validation timed out: {e}")
            return 1
        if cap.returncode != 0:
            print(f"  obs: capacity_report validation failed "
                  f"(rc={cap.returncode}):\n"
                  f"{((cap.stdout or '') + (cap.stderr or ''))[-3000:]}")
            return cap.returncode or 1
        rc = _blackbox_leg(repo, env)
        if rc != 0:
            return rc
    return 0


def _blackbox_leg(repo, env):
    """Chaos-crash a supervised run per failure class (hang, NaN streak,
    crash, SIGTERM) and assert each leaves a schema-valid black box whose
    timeline links injection -> detection -> decision — then render every
    box with tools/blackbox_report.py under a POISONED jax import, the
    proof the post-mortem path needs no accelerator stack."""
    with tempfile.TemporaryDirectory() as d:
        env = dict(env, TPUMX_BLACKBOX_DIR=d)
        env.pop("TPUMX_TRACING", None)  # the recorder must be armed
        try:
            run = subprocess.run([sys.executable, "-c", BLACKBOX_SCRIPT],
                                 env=env, cwd=repo, capture_output=True,
                                 text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            print(f"  obs: blackbox leg timed out: {e}")
            return 1
        if run.returncode != 0 or "BLACKBOX OK" not in (run.stdout or ""):
            print(f"  obs: blackbox leg failed (rc={run.returncode}):\n"
                  f"{((run.stdout or '') + (run.stderr or ''))[-4000:]}")
            return run.returncode or 1
        report = os.path.join(repo, "tools", "blackbox_report.py")
        for tag, expect in BLACKBOX_EXPECT.items():
            box = os.path.join(d, f"{tag}-blackbox.json")
            # poison jax/tpu_mx in sys.modules: if the report tool (or
            # anything it loads) tries to import either, it fails loudly
            code = ("import sys, runpy; "
                    "sys.modules['jax'] = None; "
                    "sys.modules['tpu_mx'] = None; "
                    f"sys.argv = ['blackbox_report.py', {box!r}, "
                    "'--validate']; "
                    f"runpy.run_path({report!r}, run_name='__main__')")
            try:
                ren = subprocess.run([sys.executable, "-c", code],
                                     capture_output=True, text=True,
                                     timeout=120)
            except subprocess.TimeoutExpired as e:
                print(f"  obs: blackbox report timed out on {tag}: {e}")
                return 1
            out = (ren.stdout or "") + (ren.stderr or "")
            # runpy re-raises SystemExit(0) silently; nonzero -> rc != 0
            if ren.returncode != 0:
                print(f"  obs: blackbox report failed on {tag} "
                      f"(rc={ren.returncode}):\n{out[-3000:]}")
                return 1
            missing = [m for m in expect if m not in out]
            if missing:
                print(f"  obs: blackbox report for {tag} is missing "
                      f"timeline markers {missing}:\n{out[-3000:]}")
                return 1
    return 0


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--core-only", action="store_true",
                    help="run just the <5 min core tier")
    opts = ap.parse_args()  # unknown args fail fast, not silently run all
    tiers = TIERS[:1] if opts.core_only else TIERS
    results = []
    # lint first, ALWAYS (core-only included): seconds of static checking
    # that fails the build before any pytest time is spent
    t0 = time.time()
    results.append(("lint", lint_tier(), time.time() - t0))
    for name, args, env_extra in tiers:
        t0 = time.time()
        env = None
        if env_extra:
            env = dict(os.environ)
            env.update(env_extra)
        proc = subprocess.run([sys.executable, "-m", "pytest", "-q", *args],
                              env=env)
        results.append((name, proc.returncode, time.time() - t0))
    if not opts.core_only:
        t0 = time.time()
        results.append(("native-asan", native_asan(), time.time() - t0))
        t0 = time.time()
        results.append(("obs", obs_tier(), time.time() - t0))
        t0 = time.time()
        results.append(("soak", soak_tier(), time.time() - t0))
        t0 = time.time()
        results.append(("serve", serve_tier(), time.time() - t0))
    print()
    red = False
    for name, rc, dt in results:
        status = "PASS" if rc == 0 else "FAIL"
        red = red or rc != 0
        print(f"  {status}  {name:10s} {dt:7.1f}s")
    if red:
        print("\n" + "!" * 64)
        print("!!  TEST SUITE RED — do NOT snapshot/ship this state  !!")
        print("!" * 64)
        return 1
    print("\nall tiers green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
