"""Long-context single-chip sweep: flash-kernel causal attention fwd+bwd
tokens/sec across sequence lengths (SURVEY §5.7; LONGCTX_<round>.json was
produced ad hoc last session — this makes the measurement reproducible
and extends it to T=64k).

The flash kernel's O(T) memory is what makes ≥16k context possible on one
16 GB chip at all: dense attention's backward materializes O(B·H·T²)
probabilities (≥12 GB at T=16k) and OOMs.  Ring attention (sp-sharded)
extends the same kernel across a pod slice — that path is exercised by
tests/test_parallel.py and the driver's dryrun; this tool measures the
single-chip kernel roofline.

    python tools/longctx_bench.py [--out LONGCTX_<round>.json]
                                  [--lens 4096,8192,...] [--dense-at 8192]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# exported for tpu_watch's done-predicate (drift-proofing); module top
# stays stdlib-only so the watcher can import it
DEFAULT_LENS = (4096, 8192, 16384, 32768, 65536)
DEFAULT_DENSE_AT = 8192


def log(msg):
    print(f"[longctx {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def measure(attn_fn, b, h, t, d, iters=10):
    import jax
    import jax.numpy as jnp
    from tpu_mx.runtime import fetch_sync
    key = jax.random.PRNGKey(0)
    qk, kk, vk = jax.random.split(key, 3)
    q = jax.random.normal(qk, (b, h, t, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, t, d), jnp.bfloat16)
    v = jax.random.normal(vk, (b, h, t, d), jnp.bfloat16)

    def loss_and_grads(q, k, v):
        l, g = jax.value_and_grad(
            lambda q, k, v: attn_fn(q, k, v).astype(jnp.float32).mean(),
            argnums=(0, 1, 2))(q, k, v)
        return l, g

    step = jax.jit(loss_and_grads)
    # timing is bounded by fetch_sync (host fetch of the scalar loss), not
    # block_until_ready — see tpu_mx.runtime.fetch_sync: the tunneled
    # backend's block_until_ready returns before execution finishes (the
    # first run of this tool recorded 0.04 ms "steps" at T=32k vs the
    # 44 ms a fetch-bounded run measures)
    fetch_sync(step(q, k, v)[0])                  # compile + settle
    t0 = time.perf_counter()
    for _ in range(iters):
        l, _ = step(q, k, v)
    fetch_sync(l)
    dt = (time.perf_counter() - t0) / iters
    return {"ms_per_step": round(dt * 1e3, 2),
            "tok_per_s": int(b * t / dt)}


def main():
    from artifact_protocol import artifact
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=artifact("LONGCTX"))
    ap.add_argument("--lens",
                    default=",".join(str(t) for t in DEFAULT_LENS))
    ap.add_argument("--dense-at", type=int, default=DEFAULT_DENSE_AT,
                    help="also measure XLA dense attention at this T "
                         "(0 disables); T>=16384 dense OOMs by design")
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax
    from tpu_mx.runtime import enable_shared_compilation_cache
    enable_shared_compilation_cache()
    platform = jax.devices()[0].platform
    if platform != "tpu":
        log(f"platform is {platform}, not tpu; refusing to overwrite the "
            "hardware artifact")
        return 1
    from tpu_mx.kernels.flash_attention import mha_flash_attention

    from artifact_protocol import (load_prior, merge_prior_sections,
                                   write_atomic)

    b, h, d = 1, args.heads, args.dim
    # every row carries its own geometry: merged-in rows may come from a
    # run with different --heads/--dim/--iters, and the row is the only
    # place that provenance survives the merge
    geom = {"B": b, "H": h, "D": d, "iters": args.iters}
    record = {
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S+0000", time.gmtime()),
        "config": "single chip, bf16, causal, full fwd+bwd, "
                  "loss-fetch-bounded timing, steady state; per-row "
                  "geometry in each entry",
        "platform": platform,
        "flash_kernel": {}, "dense_comparison": {},
    }
    # a partial rerun (--lens 65536 retry after a transport blip) must
    # MERGE into the existing artifact, not clobber the other rows (the
    # artifact_protocol contract); this run's rows replace their own keys.
    # require_platform: a non-tpu-labeled prior must never be grafted
    # into this platform=tpu artifact (advisor r4 finding #1)
    merge_prior_sections(record, load_prior(args.out),
                         ("flash_kernel", "dense_comparison"),
                         require_platform="tpu")
    row_ts = lambda: time.strftime("%Y-%m-%dT%H:%M:%S+0000", time.gmtime())
    flash = lambda q, k, v: mha_flash_attention(q, k, v, causal=True)
    for t in [int(x) for x in args.lens.split(",") if x.strip()]:
        log(f"flash T={t}...")
        try:
            record["flash_kernel"][f"T={t}"] = dict(
                measure(flash, b, h, t, d, args.iters), **geom,
                measured_at=row_ts())
            log(f"  {record['flash_kernel'][f'T={t}']}")
        except Exception as e:
            record["flash_kernel"][f"T={t}"] = dict(
                {"error": f"{type(e).__name__}: {e}"[:300]}, **geom,
                measured_at=row_ts())
            log(f"  T={t} failed: {type(e).__name__}")
        write_atomic(args.out, record)

    if args.dense_at:
        import jax.numpy as jnp

        def dense(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) / (d ** 0.5)
            tq = s.shape[-2]
            mask = jnp.arange(tq)[:, None] >= jnp.arange(tq)[None, :]
            p = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p,
                              v.astype(jnp.float32)).astype(q.dtype)

        t = args.dense_at
        log(f"dense T={t}...")
        try:
            rec = measure(dense, b, h, t, d, args.iters)
            # only compare against a flash row of the SAME geometry: a
            # merged-in prior row may have been measured with different
            # --heads/--dim/--iters, and a cross-geometry ratio would be
            # a wrong claim with self-consistent-looking fields
            frow = record["flash_kernel"].get(f"T={t}", {})
            ft = frow.get("ms_per_step") if all(
                frow.get(k) == v for k, v in geom.items()) else None
            if ft:
                rec["note"] = (
                    f"flash is {rec['ms_per_step'] / ft:.2f}x faster than "
                    f"dense at T={t}; dense backward's O(B*H*T^2) "
                    "probabilities stop fitting HBM at T>=16384 - flash's "
                    "O(T) memory is what makes single-chip long context "
                    "possible")
        except Exception as e:
            # e.g. --dense-at 16384: the dense backward OOMs by design —
            # record it like a flash T-failure instead of losing the run
            rec = {"error": f"{type(e).__name__}: {e}"[:300]}
            log(f"  dense T={t} failed: {type(e).__name__}")
        record["dense_comparison"][f"T={t}"] = dict(rec, **geom,
                                                    measured_at=row_ts())
    record["note"] = (
        "SURVEY 5.7 long-context on real silicon; ring attention "
        "(sp-sharded) extends this across a pod slice. Timing is "
        "loss-fetch-bounded (block_until_ready does not synchronize on "
        "the tunneled backend); supersedes the earlier under-synchronized "
        "sweep that reported 1.17M tok/s at T=16k.")
    write_atomic(args.out, record)
    log(f"done: {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
