#!/usr/bin/env python
"""Pack an image directory / list file into RecordIO
(reference analog: tools/im2rec.py — same .lst and .rec formats, so files
made here are readable by the reference and vice versa).

Two modes, like the reference:

  # 1. make a list file (label = folder index)
  python tools/im2rec.py --list data/train data/images

  # 2. pack it (resize shorter side to 480, quality 95)
  python tools/im2rec.py --resize 480 data/train data/images

.lst format: <index>\t<label>[\t<label>...]\t<relative-path>
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root, args):
    entries = []
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    if classes:
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(IMG_EXTS):
                    entries.append((float(label_of[c]),
                                    os.path.join(c, fn)))
    else:  # flat dir: label 0
        for fn in sorted(os.listdir(root)):
            if fn.lower().endswith(IMG_EXTS):
                entries.append((0.0, fn))
    if args.shuffle:
        import random
        random.seed(args.seed)
        random.shuffle(entries)
    lst = prefix + ".lst"
    with open(lst, "w") as f:
        for i, (label, path) in enumerate(entries):
            f.write(f"{i}\t{label}\t{path}\n")
    print(f"wrote {lst}: {len(entries)} images, {len(classes)} classes")


def read_list(lst):
    with open(lst) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, args):
    import cv2
    import numpy as np
    from tpu_mx import recordio

    lst = prefix + ".lst"
    if not os.path.exists(lst):
        sys.exit(f"{lst} not found — run --list first")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, labels, rel in read_list(lst):
        img = cv2.imread(os.path.join(root, rel), cv2.IMREAD_COLOR)
        if img is None:
            print(f"skip unreadable {rel}", file=sys.stderr)
            continue
        if args.resize > 0:
            h, w = img.shape[:2]
            scale = args.resize / min(h, w)
            if scale < 1 or args.upscale:
                img = cv2.resize(img, (int(w * scale + 0.5),
                                       int(h * scale + 0.5)))
        label = labels[0] if len(labels) == 1 else np.array(labels,
                                                           np.float32)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack_img(header, img,
                                             quality=args.quality,
                                             img_fmt=args.encoding))
        n += 1
    rec.close()
    print(f"wrote {prefix}.rec / {prefix}.idx: {n} records")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prefix", help="output prefix (for .lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="make the .lst file instead of packing")
    ap.add_argument("--native", action="store_true",
                    help="pack with the parallel C++ packer "
                         "(native/tpumx_io.cpp tmx_im2rec; same output "
                         "bytes as the Python path)")
    ap.add_argument("--num-thread", type=int, default=4)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter side to this many pixels")
    ap.add_argument("--upscale", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    ap.add_argument("--shuffle", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.list:
        make_list(args.prefix, args.root, args)
    else:
        if args.native:
            from tpu_mx.lib.recordio_cpp import native_im2rec
            if getattr(args, "encoding", ".jpg") not in (None, ".jpg"):
                print("warning: --native packs JPEG only; --encoding "
                      "ignored", file=sys.stderr)
            n = native_im2rec(args.prefix + ".lst", args.root, args.prefix,
                              resize=args.resize or 0,
                              quality=args.quality,
                              num_thread=args.num_thread,
                              upscale=getattr(args, "upscale", False))
            print(f"packed {n} records (native)")
        else:
            pack(args.prefix, args.root, args)


if __name__ == "__main__":
    main()
