"""Capture a real-chip profiler trace of a compiled train step and break
the step time down by XLA op category (VERDICT r3 ask#3: find where the
ResNet step's time actually goes before guessing at levers).

Runs the step under jax.profiler.trace, then parses the newest
vm.trace.json.gz chrome trace: device-track complete events ("ph":"X")
are bucketed by op-name family (fusion / convolution / copy / ...) and
written to PROFILE_STEP_<round>.json with per-family total microseconds and
the top individual ops.

Usage (ONE jax process at a time — see .claude/skills/verify):
    python tools/chip_profile.py [--model resnet|bert] [--batch N]
        [--steps N] [--out PATH]
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    print(f"[profile {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


_FAMILY = re.compile(r"^([a-zA-Z_\-]+)")


def family(name):
    """'fusion.1234' -> 'fusion'; '%convolution.5' -> 'convolution'."""
    m = _FAMILY.match(name.lstrip("%"))
    return m.group(1).rstrip(".-_") if m else name


def parse_trace(trace_dir, n_steps):
    """Aggregate device-lane complete events from the newest chrome trace
    under trace_dir.  Heuristic for device tracks: process names carrying
    'TPU' / 'Device' (host python/threads are excluded); falls back to
    every track if none match (CPU smoke)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"
                 and "args" in e}
    tid_names = {(e["pid"], e["tid"]): e["args"].get("name", "")
                 for e in events
                 if e.get("ph") == "M" and e.get("name") == "thread_name"
                 and "args" in e}
    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n or "Device" in n or "/device" in n.lower()}
    if not device_pids:
        device_pids = set(pid_names)
    # per-op timings live on the 'XLA Ops' lane; the 'Steps' / 'XLA
    # Modules' lanes are whole-step envelopes that would double-count
    op_lanes = {k for k, n in tid_names.items()
                if k[0] in device_pids and n == "XLA Ops"}
    fam_us, op_us, op_count = {}, {}, {}
    for e in events:
        if e.get("ph") != "X":
            continue
        if op_lanes:
            if (e.get("pid"), e.get("tid")) not in op_lanes:
                continue
        elif e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))
        fam = family(name)
        fam_us[fam] = fam_us.get(fam, 0.0) + dur
        op_us[name] = op_us.get(name, 0.0) + dur
        op_count[name] = op_count.get(name, 0) + 1
    per_step = {k: round(v / n_steps, 1) for k, v in fam_us.items()}
    top = sorted(op_us.items(), key=lambda kv: -kv[1])[:25]
    return {
        "trace_file": paths[-1],
        "families_us_per_step": dict(
            sorted(per_step.items(), key=lambda kv: -kv[1])),
        "total_device_us_per_step": round(sum(fam_us.values()) / n_steps, 1),
        "top_ops": [{"name": n, "us_per_step": round(v / n_steps, 1),
                     "calls_per_step": round(op_count[n] / n_steps, 1)}
                    for n, v in top],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet",
                    choices=["resnet", "bert"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--out", default=None,
                    help="default: PROFILE_STEP_<round>.json for resnet, "
                         "PROFILE_<MODEL>_<round>.json otherwise")
    ap.add_argument("--trace-dir", default="/tmp/tpumx_chip_trace")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.out is None:
        from artifact_protocol import artifact
        args.out = artifact("PROFILE_STEP" if args.model == "resnet"
                            else f"PROFILE_{args.model.upper()}")

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    else:
        from tpu_mx.runtime import enable_shared_compilation_cache
        enable_shared_compilation_cache()
    import numpy as np
    import hlo_inspect

    smoke = args.cpu
    log(f"building {args.model} batch={args.batch}...")
    if args.model == "resnet":
        step, batch_args = hlo_inspect.build_resnet_step(smoke, args.batch)
    else:
        step, batch_args = hlo_inspect.build_bert_step(smoke, args.batch)
    fetch = lambda l: float(np.asarray(l._data).ravel()[0])
    log("compiling + warmup...")
    fetch(step.step(*batch_args))
    fetch(step.step(*batch_args))

    log(f"tracing {args.steps} steps...")
    os.makedirs(args.trace_dir, exist_ok=True)
    with jax.profiler.trace(args.trace_dir):
        loss = None
        for _ in range(args.steps):
            loss = step.step(*batch_args)
        fetch(loss)

    log("parsing trace...")
    rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "platform": jax.devices()[0].platform,
           "model": args.model, "batch": args.batch, "steps": args.steps}
    rec.update(parse_trace(args.trace_dir, args.steps))
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    log(f"wrote {args.out}")
    fams = rec["families_us_per_step"]
    for k in list(fams)[:12]:
        log(f"  {k:<28} {fams[k]:>10.1f} us/step")
    log(f"  {'TOTAL(device)':<28} {rec['total_device_us_per_step']:>10.1f}"
        f" us/step")


if __name__ == "__main__":
    main()
