"""Benchmark: ResNet-50 + BERT-base training throughput, single chip (the two
BASELINE.md headline metrics).

Runs the full compiled train step (fwd+bwd+optimizer update in one XLA
program, bf16 compute / f32 master state) for BOTH headline workloads and
prints ONE JSON line:
  {"metric": "resnet50_...", "value": N, "unit": "img/s", "vs_baseline": N,
   "mfu": ..., "bert": {"metric": "bert_base_...", ...}}
The primary record is ResNet-50 (driver contract); the BERT-base record rides
in the "bert" field (VERDICT r2 ask#2: both metrics, flash path confirmed).
vs_baseline is against the A100 ballparks in BASELINE.md.

ResNet-50 runs channels-last with the space-to-depth stem by default
(BENCH_STEM=classic reverts): the classic 7×7/2 stem feeds C=3 into the
128-lane MXU contraction ~43× under-filled; the 4×4 space-to-depth transform
makes the first conv contract over 48 channels (VERDICT r2 ask#1).

Engineering for the tunneled TPU backend (BENCH_r01 failure + VERDICT weak#1):
backend init can hang indefinitely inside a C call, which no in-process
timeout can interrupt.  So the outer process (this file, run with no args)
imports NO jax; it supervises `python bench.py --inner` children with a hard
timeout and retry/backoff, streams the child's stage prints to stderr, and
ALWAYS emits a JSON line — a real number, or a partial record with "error"
set if every attempt died.

Env knobs: BENCH_SMOKE=1 (CPU smoke, small shapes), BENCH_LAYOUT=NCHW
(default NHWC), BENCH_STEM=classic (default s2d), BENCH_BATCH / BENCH_ITERS /
BENCH_BERT_BATCH / BENCH_BERT512_BATCH / BENCH_LSTM_BATCH /
BENCH_SSD_BATCH overrides, BENCH_BERT512_REMAT (default 1),
BENCH_SSD_BACKBONE (default vgg16_reduced — the reference config;
=compact for the r4 light backbone, comparator-less),
BENCH_MODELS ⊆ {resnet50, bert, bert512, scaling, lstm, ssd, fusion}
(fusion = the imperative pointwise-fusion A/B microbench, CPU-targeted,
not in the default on-chip set; default
resnet50,bert,bert512,lstm,ssd — all five workload benches, so the
driver's round-end record carries every hardware number; per-metric
persistence keeps a mid-sweep wedge from losing the earlier legs;
scaling = weak-scaling efficiency over all visible devices, BASELINE
metric 3, needs a multi-device mesh),
BENCH_ATTEMPTS (default 2), BENCH_TIMEOUT seconds per attempt (default 2400),
BENCH_SKIP_FRESH seconds (default 0 = off): carry a leg's stored record
instead of re-measuring when it is younger than this, so a retry after a
mid-run wedge spends its tunnel window on the legs still missing (carried
legs keep their own measured_at + carried_fresh=true; the quick-bench's
short-timing resnet record never qualifies via the min-iters gate).
Execution order is resnet, bert, lstm, ssd, bert512 — the giant bert512
remat compile runs last so a wedge inside it cannot cost unmeasured legs.
MFU fields: `mfu` is XLA-cost-analysis-derived (the number of record,
VERDICT r4 ask#9); `mfu_analytic_model` is the hand FLOPs-model cross-check.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

def _lastgood_path():
    return os.environ.get(
        "BENCH_LASTGOOD_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_LASTGOOD.json"))

A100_RESNET50 = 2800.0   # img/s, BASELINE.md ballpark (AMP, 1×A100-80GB)
A100_BERT_BASE = 245.0   # seq/s, BASELINE.md ballpark midpoint (phase-1 128)
# Derived comparator ballparks for the workloads with no published A100
# number (VERDICT r4 ask#6; derivations with stated assumptions in
# BASELINE.md "Derived ballparks"):
A100_LSTM_PTB = 780_000.0   # tok/s: 79.6 MFLOPs/tok model @ 20% A100 util
A100_SSD512_VGG = 170.0     # img/s: NGC SSD300-RN50 utilization (~29%)
#                             transferred to the VGG16-reduced SSD-512 model
V5E_PEAK_FLOPS = 197e12  # bf16 peak, TPU v5e chip
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9  # fwd GMACs*2, *3 for fwd+bwd


def a100_bert_512_ballpark():
    """Phase-2 (seq 512) comparator: iso-utilization transfer of the
    phase-1 A100 ballpark through the FLOPs model — ballpark_512 =
    ballpark_128 x flops(128)/flops(512) (~57 seq/s).  Documented in
    BASELINE.md; attention makes A100 utilization at 512 slightly worse,
    so this transfer is comparator-favoring (honest direction)."""
    f128 = bert_train_flops_per_seq(12, 768, 3072, 30522, 128,
                                    max(1, int(0.15 * 128)))
    f512 = bert_train_flops_per_seq(12, 768, 3072, 30522, 512,
                                    max(1, int(0.15 * 512)))
    return A100_BERT_BASE * f128 / f512


def bert_train_flops_per_seq(num_layers, units, hidden, vocab, seq_len,
                             n_masked):
    """Matmul-only train flops per sequence, counted per executed matmul
    (fwd 2·flops, bwd 4·flops): per-layer qkv/attn-out/ffn + the T² score
    and AV terms over all T positions, the MLM dense + tied vocab head over
    ONLY the n_masked positions (embedding lookups are gathers, not
    matmuls, and are excluded)."""
    per_tok_layer = 2 * units * (3 * units) + 2 * units * units \
        + 2 * 2 * units * hidden
    body = num_layers * seq_len * (per_tok_layer + 4 * seq_len * units)
    head = n_masked * (2 * units * units + 2 * vocab * units)
    return 3 * (body + head)


def log(msg):
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


_PERSIST_PLATFORM_OK = None


def _persist_platform_ok():
    """Only a process whose backend is the real TPU may write the store.
    The smoke guard below is not enough: a non-smoke CPU drive with the
    production metric name (e.g. a BENCH_BATCH=4 JAX_PLATFORMS=cpu
    verification run — r5 hit exactly this) would clobber a real-chip
    record.  Same platform contract as mfu_probe/longctx merge-on-write.
    BENCH_PERSIST_ANY_PLATFORM=1 bypasses for the store-logic tests."""
    global _PERSIST_PLATFORM_OK
    if os.environ.get("BENCH_PERSIST_ANY_PLATFORM") == "1":
        return True
    if _PERSIST_PLATFORM_OK is None:
        try:
            import jax
            platform = jax.devices()[0].platform
        except Exception as e:
            # transient probe failure: refuse THIS persist (loudly) but
            # don't cache — a later call in the same run may succeed
            log(f"persist refused: backend probe failed "
                f"({type(e).__name__}: {e}); record NOT stored")
            return False
        _PERSIST_PLATFORM_OK = platform == "tpu"
        if not _PERSIST_PLATFORM_OK:
            log(f"persist refused: platform is {platform}, not tpu — "
                "records from this process will NOT touch the store")
    return _PERSIST_PLATFORM_OK


def persist_lastgood(rec):
    """Write the measurement to BENCH_LASTGOOD.json the moment it exists
    (VERDICT r3 weak#2: round 3's official record was 0.0/error while a
    real number measured 11 h earlier sat only in an interim note — every
    good measurement must survive the process that produced it).  Atomic
    via tmp+rename so a kill mid-write can't corrupt the last record.
    Smoke-mode runs never persist: a CPU smoke number (whose metric name
    may not say "smoke" — e.g. weak_scaling_efficiency_dp8) must never
    mask a real-chip record.  The store is keyed by metric so a
    BENCH_MODELS=bert (or scaling) run can never clobber the resnet
    record.  Persist failures are logged, never raised: the resilience
    layer must not be able to kill a successful measurement run."""
    if os.environ.get("BENCH_SMOKE") == "1" or \
            "smoke" in rec.get("metric", ""):
        return
    if not _persist_platform_ok():
        return
    if rec.get("metric") == "weak_scaling_efficiency_dp1":
        # single-device placeholder (trivially 1.0), not a measurement —
        # it must never enter the store, where freshest-wins grafting
        # would let it shadow a real multi-device scaling record
        return
    try:
        path = _lastgood_path()
        try:
            with open(path) as f:
                store = json.load(f)
        except (OSError, ValueError):
            store = {}
        if not isinstance(store, dict):
            store = {}
        records = store.get("records")
        if not isinstance(records, dict):
            records = {}
        records[rec["metric"]] = {
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "commit": _git_head(),
            "record": rec}
        # durability-layer atomic write (tmp + fsync + rename, ISSUE 2): a
        # bench run killed mid-persist can never leave a truncated
        # BENCH_LASTGOOD.json that poisons the carry logic
        from tpu_mx.checkpoint import atomic_write
        with atomic_write(path, "w") as f:
            f.write(json.dumps({"records": records}, indent=1))
    except Exception as e:
        log(f"persist_lastgood failed (measurement still emitted): "
            f"{type(e).__name__}: {e}")


PRIMARY_METRIC = "resnet50_train_images_per_sec_per_chip"

# Canonical full-run timing iterations per leg (the official-record bar).
# Carried-record min-iters gates key on THESE, never on the env-derived
# BENCH_ITERS: a retry launched with both BENCH_SKIP_FRESH and a lowered
# BENCH_ITERS must not accept an equally short stored record as official
# (ADVICE r5 low, bench.py:1003).  Records timed below the bar also get
# vs_baseline stripped — the r5 quick-vs-full spread was 8.5% from
# iteration count alone, enough to fake a regression (VERDICT r5 weak#2).
FULL_RUN_ITERS = {"resnet50": 30, "lstm": 20, "ssd": 10}


def _strip_short_run_baseline(rec, leg):
    if rec.get("iters", 0) < FULL_RUN_ITERS[leg] and \
            rec.get("vs_baseline") is not None:
        rec["vs_baseline"] = None
        rec["vs_baseline_note"] = (
            f"short-timing run (iters < {FULL_RUN_ITERS[leg]}): too noisy "
            "for a baseline comparison; see VERDICT r5 weak#2")
    return rec


_GIT_HEAD = ("unresolved",)


def _git_head():
    """Commit of the current checkout (cached; None when unresolvable).
    Persisted records carry it so a carried record can be tied to the
    code that produced it (ADVICE r5 low, bench.py:310)."""
    global _GIT_HEAD
    if _GIT_HEAD == ("unresolved",):
        try:
            out = subprocess.run(
                ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
                 "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=10)
            head = out.stdout.strip()
            _GIT_HEAD = (head if out.returncode == 0 and head else None,)
        except Exception:
            _GIT_HEAD = (None,)
    return _GIT_HEAD[0]


def load_lastgood():
    """Best stored measurement: the primary resnet metric if present,
    else the most recently measured other metric.  Returns (measured_at,
    record) or (None, None).  Tolerates any malformed store content —
    this is the outer supervisor's last-ditch path and must never raise
    (the driver contract is 'ALWAYS emit a JSON line')."""
    try:
        with open(_lastgood_path()) as f:
            store = json.load(f)
        records = store.get("records", {})
        entries = [v for v in records.values()
                   if isinstance(v, dict) and isinstance(v.get("record"),
                                                         dict)]
        entries = [v for v in entries
                   if isinstance(v["record"].get("value"), (int, float))
                   and v["record"]["value"] > 0]
        if not entries:
            return None, None

        def _graft_subs(v):
            # the store holds bert/scaling under their own metric keys
            # (always at least as fresh as any copy nested inside the
            # primary record, since the same run writes both) — serve the
            # per-key record of each alongside the primary.  Scaling keys
            # are dynamic (weak_scaling_efficiency_dp{n}), hence the
            # prefix match.
            rec = dict(v["record"])
            own = str(rec.get("metric") or "")

            def _field_of(metric, record=None):
                record = record or {}
                if metric == "bert_base_train_seqs_per_sec_per_chip":
                    return "bert"
                if metric == "bert_base_seq512_train_seqs_per_sec_per_chip":
                    return "bert512"
                if metric.startswith("weak_scaling_efficiency"):
                    # dynamic dp{n} key family — freshest wins, not
                    # dict order
                    return "scaling"
                if metric == "lstm_ptb_train_tokens_per_sec_per_chip":
                    return "lstm"
                if metric == "ssd512_train_images_per_sec_per_chip":
                    # the official key means the vgg16_reduced reference
                    # backbone from r5 on; a backbone-less record is the
                    # r4 compact measurement — surface it clearly labeled,
                    # never in the official slot (its 170 img/s comparator
                    # would be a wrong claim for a ~3x lighter model)
                    if record.get("backbone") == "vgg16_reduced":
                        return "ssd"
                    return "ssd_legacy_compact"
                if metric.startswith("ssd512_") and \
                        metric.endswith("_train_images_per_sec_per_chip"):
                    return "ssd_compact"  # explicitly-keyed non-vgg rows
                return None

            own_field = _field_of(own, rec)
            best = {}  # field -> store entry; freshest measured_at wins
            for key, sub in records.items():
                if key == own or not (isinstance(sub, dict)
                                      and isinstance(sub.get("record"),
                                                     dict)):
                    continue
                # same validity bar as primary selection: a null/zero
                # record must not be grafted either
                if not isinstance(sub["record"].get("value"),
                                  (int, float)) or sub["record"]["value"] <= 0:
                    continue
                field = _field_of(key, sub["record"])
                # never graft a sibling of the primary's own family (a
                # scaling primary carrying a staler scaling nested inside
                # itself would be contradictory, not supplementary)
                if field is None or field == own_field:
                    continue
                if field not in best or str(sub.get("measured_at", "")) > \
                        str(best[field].get("measured_at", "")):
                    best[field] = sub
            for field, sub in best.items():
                # carry the sub's own timestamp: it may come from a
                # different run than the primary, and this harness exists
                # because freshness misattribution cost round 3 its record
                rec[field] = dict(sub["record"],
                                  measured_at=sub.get("measured_at"))
                if field == "ssd_legacy_compact":
                    rec[field].setdefault("backbone", "compact")
                    rec[field]["note"] = (
                        "r4-era measurement on the light compact "
                        "backbone; not comparable to the vgg16_reduced "
                        "official row or its A100 ballpark")
            return v.get("measured_at"), rec

        for v in entries:
            if v["record"].get("metric") == PRIMARY_METRIC:
                return _graft_subs(v)
        v = max(entries, key=lambda v: str(v.get("measured_at", "")))
        return _graft_subs(v)
    except Exception:
        return None, None


def _fresh_stored(metric_key, max_age_s, require=None, min_iters=None,
                  validate=None):
    """Stored record for metric_key if it was measured on chip within
    max_age_s seconds, else None (BENCH_SKIP_FRESH: a wedge-shortened
    retry spends its tunnel window on the legs that still need measuring
    instead of re-timing ones banked minutes earlier in the same window).
    `require` narrows the match on record fields (e.g. ssd backbone: the
    official metric key predates the vgg16_reduced re-key, so an r4-era
    compact record must not satisfy it); `min_iters` keeps a short-timing
    quick-bench record from being carried as the official number;
    `validate(rec) -> bool` hooks leg-specific completeness checks (e.g.
    bert512's flash arm).  A record stamped with a different git commit
    than the current checkout is never carried — an intervening
    perf-affecting commit must be re-measured, not inherit the old
    number (ADVICE r5 low, bench.py:310); unstamped records (pre-stamp
    stores) carry with commit=None, auditable downstream."""
    try:
        with open(_lastgood_path()) as f:
            entry = json.load(f)["records"][metric_key]
        rec = entry["record"]
        if not isinstance(rec.get("value"), (int, float)) \
                or rec["value"] <= 0 or "error" in rec:
            return None
        for k, v in (require or {}).items():
            if rec.get(k) != v:
                return None
        if min_iters is not None and rec.get("iters", 0) < min_iters:
            return None
        if validate is not None and not validate(rec):
            return None
        stored_commit = entry.get("commit")
        head = _git_head()
        if stored_commit and head and stored_commit != head:
            log(f"{metric_key}: stored record is from commit "
                f"{stored_commit[:12]}, checkout is {head[:12]} — "
                "refusing to carry across code versions")
            return None
        import datetime
        measured = datetime.datetime.strptime(
            str(entry["measured_at"]), "%Y-%m-%dT%H:%M:%S%z")
        if 0 <= time.time() - measured.timestamp() <= max_age_s:
            return dict(rec, measured_at=entry["measured_at"],
                        carried_fresh=True, commit=stored_commit)
    except Exception:
        return None
    return None


# ---------------------------------------------------------------------------
# inner: the actual benchmark (may hang on a flaky backend; outer kills us)
# ---------------------------------------------------------------------------
def _fetch_loss(l):
    """Host-fetch the loss scalar — the sync point for every benchmark
    here (see the comment in _timed: block_until_ready lies on the
    tunneled backend; a host fetch bounds the full update chain)."""
    import numpy as np
    return float(np.asarray(l._data).ravel()[0])


def _timed(step_fn, fetch_loss, n):
    t0 = time.perf_counter()
    loss = None
    for _ in range(n):
        loss = step_fn()
    # Sync via a host fetch of the loss scalar, not wait_to_read: on the
    # tunneled single-chip backend block_until_ready returns before the
    # computation finishes, which silently inflates throughput ~10x.  The
    # loss depends on the full weight-update chain, so fetching it bounds
    # every queued step.
    fetch_loss(loss)
    return time.perf_counter() - t0


def _run_timed(step_fn, fetch_loss, warmup, iters, repeats, unit_count, tag):
    _timed(step_fn, fetch_loss, 1)
    log(f"{tag}: first step done; warmup...")
    for _ in range(warmup):
        _timed(step_fn, fetch_loss, 1)
    log(f"{tag}: timing {iters} steps x {repeats} repeats...")
    best = None
    for r in range(repeats):
        dt = _timed(step_fn, fetch_loss, iters)
        log(f"  {tag} repeat {r}: {dt:.3f}s ({unit_count * iters / dt:.1f}/s)")
        best = dt if best is None else min(best, dt)
    return unit_count * iters / best


def _attach_mfu(rec, step, batch_args, per_sec, unit_flops, batch):
    """MFU fields (VERDICT r4 ask#9 — ONE definition of record):
    `mfu` is computed from XLA's own cost-analysis FLOPs of the compiled
    step (compiler-derived, immune to hand-model drift); the analytic
    FLOPs model rides as `mfu_analytic_model` for cross-check.  Falls
    back to the analytic model (with mfu_source saying so) only when
    cost_analysis is unavailable on the backend."""
    analytic = per_sec * unit_flops / V5E_PEAK_FLOPS
    rec["mfu_analytic_model"] = round(analytic, 4)
    try:
        ca = step.aot_compiled(*batch_args).cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = float(ca.get("flops", 0.0))
    except Exception as e:
        log(f"cost_analysis unavailable ({type(e).__name__}: {e}); "
            f"mfu falls back to the analytic model")
        flops = 0.0
    if flops > 0:
        rec["mfu"] = round(flops * per_sec / batch / V5E_PEAK_FLOPS, 4)
        rec["mfu_source"] = "xla_cost_analysis"
        rec["analytic_vs_xla_flops_ratio"] = round(
            unit_flops * batch / flops, 4)
    else:
        rec["mfu"] = round(analytic, 4)
        rec["mfu_source"] = "analytic_model"
    return rec


def _bench_dtype(env_var, smoke):
    """(dtype, multi_precision) for a bench leg: bfloat16 on hardware by
    default, float32 in CPU smoke (keeps the nightly fast and smoke
    numerics boring); per-leg env override (=float32 reverts on chip).
    The resnet leg predates this helper and casts unconditionally."""
    dt = os.environ.get(env_var, "float32" if smoke else "bfloat16")
    return dt, dt != "float32"


def _is_oom(e):
    # explicit allocation-failure phrases only: a bare "hbm" mention (e.g.
    # a bandwidth note inside some other error) must NOT trigger the
    # silent batch fallback
    s = f"{type(e).__name__}: {e}".lower()
    return ("ran out of memory" in s or "out of memory" in s
            or "resource_exhausted" in s or "exceeded hbm capacity" in s)


def _batch_ladder(env_var, ladder):
    """BENCH_BATCH/BENCH_BERT_BATCH=N forces one size; unset runs the
    ladder largest-first, falling back on HBM OOM (larger batches usually
    win on MXU utilization but the margin to 16 GB is model-dependent —
    measure, don't guess)."""
    v = os.environ.get(env_var)
    return [int(v)] if v else list(ladder)


def _run_ladder(tag, ladder, once):
    """Try batch sizes largest-first; fall back on HBM OOM.  The last
    rung re-raises (no fallback left)."""
    for i, batch in enumerate(ladder):
        try:
            return once(batch)
        except Exception as e:
            if i + 1 < len(ladder) and _is_oom(e):
                log(f"{tag} batch {batch} OOM ({e}); "
                    f"falling back to {ladder[i + 1]}")
                continue
            raise


def bench_resnet(smoke, layout, stem):
    # 256-first: the r4 on-chip sweep measured 256 > 384 > 512
    # (2379 / 2275 / 2254 img/s) — past ~256 the extra HBM pressure
    # costs more than the MXU fill gains.
    ladder = _batch_ladder("BENCH_BATCH", (8,) if smoke else (256, 128))
    return _run_ladder("resnet", ladder,
                       lambda b: _resnet_once(smoke, layout, stem, b))


def _resnet_once(smoke, layout, stem, batch):
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.layout import default_layout
    from tpu_mx.parallel import CompiledTrainStep

    if smoke:
        size, warmup, iters = 64, 1, 3
        classes, factory = 100, "resnet18_v1"
    else:
        size, warmup, iters = 224, 3, 30
        classes, factory = 1000, "resnet50_v1"
    iters = int(os.environ.get("BENCH_ITERS", iters))

    log(f"building {factory} ({layout}, stem={stem}), batch={batch}, "
        f"size={size}")
    shape = (batch, size, size, 3) if layout == "NHWC" else (batch, 3, size, size)
    with default_layout(layout):
        net = getattr(vision, factory)(classes=classes, stem=stem)
    if os.environ.get("BENCH_RESNET_REMAT", "0") == "1" and not smoke:
        # A/B knob, measured and REJECTED as a default (r4: 1847.2 vs
        # 2371.5 img/s at batch 256): recomputed conv outputs re-
        # materialize in HBM during the backward, so full-block remat ADDS
        # a pass over the conv activations on this bandwidth-bound step
        # (docs/performance.md roofline). Kept for memory-bound configs
        # where remat buys otherwise-impossible batch.
        from tpu_mx.gluon import nn as _nn
        n_remat = 0
        for stage in net.features._children.values():
            if isinstance(stage, _nn.HybridSequential):
                for blk in stage._children.values():
                    blk.remat()
                    n_remat += 1
        log(f"resnet: remat enabled on {n_remat} residual blocks")
    net.initialize(init="xavier")
    # Finalize deferred shapes on a tiny ON-DEVICE batch: param shapes
    # don't depend on batch, and the old full-batch host tensor cost
    # ~150 MB of tunnel transfer + a batch-256 eager forward before the
    # first measurement (r5: the tunnel wedged inside exactly that
    # window — keep cold-start device traffic minimal).
    net.finalize_shapes(nd.random.uniform(shape=(2,) + shape[1:]))
    net.cast("bfloat16")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4, multi_precision=True)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)

    data = nd.cast(nd.random.uniform(shape=shape), "bfloat16")
    label = nd.random.randint(0, classes, (batch,), dtype="float32")

    log("resnet: compiling full train step (first call)...")
    img_s = _run_timed(lambda: step.step(data, label), _fetch_loss, warmup, iters,
                       1 if smoke else 3, batch, "resnet")
    rec = {
        "metric": "resnet50_train_images_per_sec_per_chip"
        if not smoke else "resnet18_smoke_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / A100_RESNET50, 4),
    }
    if not smoke:
        _attach_mfu(rec, step, (data, label), img_s,
                    RESNET50_TRAIN_FLOPS_PER_IMG, batch)
    rec["layout"] = layout
    rec["stem"] = stem
    rec["batch"] = batch
    rec["iters"] = iters  # self-describing: a 5-iter quick probe must be
    #                       distinguishable from the official 30-iter run
    if not smoke:
        _strip_short_run_baseline(rec, "resnet50")
    return rec


def bench_bert(smoke):
    # The r4 sweep (384 -> 724.9 seq/s > 256 -> 707 > 512 OOM remat-free)
    # was measured when "bf16" BERT silently ran f32 activations (the
    # dtype= bug fixed in r5): true-bf16 halves activation bytes, so the
    # ladder now probes 768/512 first — largest-first with OOM fallback
    # keeps the measured 384 as the safety net.
    ladder = _batch_ladder("BENCH_BERT_BATCH",
                           (8,) if smoke else (768, 512, 384, 256))
    return _run_ladder("bert", ladder, lambda b: _bert_once(smoke, b))


def bench_bert512(smoke):
    """Phase-2-style BERT-base seq-512 row (VERDICT r4 ask#5): the memory
    regime where flash attention + remat matter, in the official record.
    The primary value is the production auto-dispatch path; when auto
    resolves to XLA dense (kv_len 512 sits at the measured crossover), a
    pinned-flash arm is measured alongside so the Pallas kernel appears
    in a driver-visible workload number either way."""
    ladder = _batch_ladder("BENCH_BERT512_BATCH",
                           (4,) if smoke else (192, 128, 96, 64, 32))
    remat = os.environ.get("BENCH_BERT512_REMAT", "1") == "1"
    rec = _run_ladder("bert512", ladder,
                      lambda b: _bert_once(smoke, b, seq_len=512,
                                           remat=remat))
    if smoke or rec.get("attention_path") == "pallas_flash":
        return rec
    # persist the measured auto-arm record BEFORE the flash arm runs: a
    # flash-compile wedge killing the process must not take the already-
    # measured number with it (the r4 per-metric-persist lesson)
    log("bert512 record (auto arm): " + json.dumps(rec))
    persist_lastgood(rec)
    prior = os.environ.get("TPUMX_ATTENTION")
    os.environ["TPUMX_ATTENTION"] = "flash"
    try:
        frec = _run_ladder("bert512_flash", ladder,
                           lambda b: _bert_once(smoke, b, seq_len=512,
                                                remat=remat))
        rec["flash_arm"] = {k: frec.get(k) for k in
                            ("value", "unit", "batch", "attention_path",
                             "mfu", "mfu_source", "mfu_analytic_model")}
    except Exception as e:
        rec["flash_arm"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        if prior is None:
            os.environ.pop("TPUMX_ATTENTION", None)
        else:
            os.environ["TPUMX_ATTENTION"] = prior
    return rec


def _bert_once(smoke, batch, seq_len=128, remat=None):
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.models.bert import BERTModel, bert_base_config
    from tpu_mx.parallel import CompiledTrainStep
    from tpu_mx.parallel.ring_attention import dispatch_counts

    if smoke:
        cfg = bert_base_config(vocab_size=1000, max_len=seq_len)
        cfg.update(num_layers=2, units=128, hidden_size=512, num_heads=2)
        warmup, iters, repeats = 1, 3, 1
    else:
        cfg = bert_base_config(max_len=seq_len)
        warmup, iters, repeats = 3, 20, 3
        if seq_len >= 512:
            iters = 10  # 4x the tokens per step; keep the leg's wall time

    # remat defaults OFF at seq 128: the r4 on-chip sweep measured
    # remat-free batch 384 at 724.9 seq/s vs remat batch 512 at 578.3
    # (recompute cost ~22% and the bigger batch does not pay for it) —
    # measured under the f32-activation dtype bug; the r5 true-bf16
    # ladder probes larger batches first and relies on OOM fallback.
    # dots_saveable measured strictly worse (OOM at 512 AND 256).  At seq
    # 512 the caller decides (bench_bert512 defaults remat ON — the
    # activation regime is 4x per sequence).
    if remat is None:
        remat = os.environ.get("BENCH_BERT_REMAT", "0") == "1"
    # BENCH_BERT_REMAT_POLICY=dots_saveable keeps MXU outputs across the
    # checkpoint boundary (less recompute, more HBM) — sweep on-chip
    policy = os.environ.get("BENCH_BERT_REMAT_POLICY") or None
    log(f"building bert ({cfg['num_layers']}L u{cfg['units']}), "
        f"batch={batch}, seq={seq_len}, remat={remat}, policy={policy}")
    # per-layer jax.checkpoint: batch 512 × seq 128 activations for 12
    # layers exceed the 16 GB HBM (measured 27 GB); remat trades ~1 extra
    # forward for O(1)-segment activation memory
    net = BERTModel(cfg, dtype="bfloat16", remat=remat,
                    remat_policy=policy)
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = rng.randint(4, cfg["vocab_size"], (batch, seq_len)).astype(
        np.int32)
    types = np.zeros((batch, seq_len), np.int32)
    # reference pretraining contract: the vocab head runs ONLY on the 15%
    # masked positions (B, M) — full-T logits would be ~4 GB at this scale
    n_masked = max(1, int(0.15 * seq_len))
    positions = np.stack([rng.choice(seq_len, n_masked, replace=False)
                          for _ in range(batch)]).astype(np.int32)
    labels = np.take_along_axis(tokens, positions, axis=1)
    # ONE row through the masked head if anything is deferred — BERT
    # declares every dim so this is normally a no-op (an eager 12-layer
    # forward over the tunnel is pure cold-start waste)
    net.finalize_shapes(nd.array(tokens[:1]), nd.array(types[:1]), None,
                        nd.array(positions[:1]))

    class MLMLoss(gluon.loss.Loss):
        """CE over the gathered masked positions (every label is a real
        token id on this path — no ignore-index sentinel needed)."""

        def __init__(self, **kw):
            super().__init__(weight=None, batch_axis=0, **kw)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, logits, labels):
            vocab = logits.shape[-1]
            return F.mean(self._ce(F.reshape(logits, shape=(-1, vocab)),
                                   F.reshape(labels, shape=(-1,))))

    opt = mx.optimizer.create("lamb", learning_rate=1e-4,
                              multi_precision=True)
    step = CompiledTrainStep(net, MLMLoss(), opt)
    t_nd, ty_nd = nd.array(tokens), nd.array(types)
    p_nd, l_nd = nd.array(positions), nd.array(labels)
    none_vl = None  # full sequences: no padding in the bench batch

    # dispatch counters are process-global and cumulative: snapshot before
    # this leg so a bert512 flash arm after a dense bert128 leg (or vice
    # versa) reports ITS OWN compiled path, not an earlier leg's
    counts0 = dict(dispatch_counts)
    log(f"bert(seq={seq_len}): compiling full train step (first call)...")
    seq_s = _run_timed(
        lambda: step.step(t_nd, ty_nd, none_vl, p_nd, l_nd), _fetch_loss,
        warmup, iters, repeats, batch, f"bert{seq_len}")

    # which attention path compiled in (VERDICT r2 ask#2: prove flash, not
    # the dense O(T²) fallback)
    if dispatch_counts["pallas_flash"] > counts0.get("pallas_flash", 0):
        path = "pallas_flash"
    elif dispatch_counts["ring"] > counts0.get("ring", 0):
        path = "ring"
    else:
        path = "xla_dense"
    flops = bert_train_flops_per_seq(cfg["num_layers"], cfg["units"],
                                     cfg["hidden_size"],
                                     cfg["vocab_size"], seq_len, n_masked)
    if smoke:
        metric, baseline = f"bert_smoke_seq{seq_len}_seqs_per_sec", None
    elif seq_len == 512:
        metric = "bert_base_seq512_train_seqs_per_sec_per_chip"
        baseline = a100_bert_512_ballpark()
    else:
        metric = "bert_base_train_seqs_per_sec_per_chip"
        baseline = A100_BERT_BASE
    rec = {
        "metric": metric,
        "value": round(seq_s, 2),
        "unit": "seq/s",
        "vs_baseline": round(seq_s / baseline, 4) if baseline else None,
        "attention_path": path,
        "seq_len": seq_len,
        "batch": batch,
        "iters": iters,
        "remat": bool(remat),
    }
    if not smoke:
        _attach_mfu(rec, step, (t_nd, ty_nd, none_vl, p_nd, l_nd), seq_s,
                    flops, batch)
    return rec


def bench_lstm(smoke):
    # 2048-first: the r4 third-session on-chip sweep measured
    # 512 -> 648k, 1024 -> 710k, 2048 -> 743k, 4096 -> 714k tok/s —
    # the scan amortizes per-step overhead up to 2048, then HBM pressure
    # wins.  Batch is recorded in the emitted record; PTB convergence
    # configs are far smaller (the classic is 20-32) and this metric is
    # per-chip THROUGHPUT at the annotated batch.
    ladder = _batch_ladder("BENCH_LSTM_BATCH",
                           (4,) if smoke else (2048, 1024, 512))
    return _run_ladder("lstm", ladder, lambda b: _lstm_once(smoke, b))


def _lstm_once(smoke, batch):
    """PTB word-level LSTM LM (BASELINE workload 3): medium config
    (vocab 10k, 2×650, bptt 35), full compiled train step, tokens/s.
    vs_baseline is against the DERIVED A100 ballpark in BASELINE.md
    (79.6 MFLOPs/tok analytic model at an assumed 20% cuDNN end-to-end
    utilization — no published A100 PTB number exists to cite; the
    derivation and its uncertainty band are documented there)."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.block import HybridBlock
    from tpu_mx.models.lstm_lm import RNNModel
    from tpu_mx.parallel import CompiledTrainStep

    if smoke:
        vocab, emb, hid, layers, bptt = 1000, 64, 64, 1, 8
        warmup, iters, repeats = 1, 3, 1
    else:
        vocab, emb, hid, layers, bptt = 10000, 650, 650, 2, 35
        warmup, iters, repeats = 3, 20, 3
    iters = int(os.environ.get("BENCH_ITERS", iters))

    log(f"building lstm ({layers}x{hid}, bptt={bptt}), batch={batch}")
    model = RNNModel(mode="lstm", vocab_size=vocab, num_embed=emb,
                     num_hidden=hid, num_layers=layers, dropout=0.0)
    model.initialize(init="xavier")

    class FlatCE(gluon.loss.Loss):
        """CE over the flattened (T·B, V) logits — the word-LM target
        layout (REF:example/gluon/word_language_model).  Logits upcast to
        f32: log-softmax over a 10k vocab in bf16 loses the digits the
        loss needs."""

        def __init__(self, **kw):
            super().__init__(weight=None, batch_axis=0, **kw)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, logits, labels):
            v = logits.shape[-1]
            return self._ce(
                F.cast(F.reshape(logits, shape=(-1, v)), dtype="float32"),
                F.reshape(labels, shape=(-1,)))

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, vocab, (bptt, batch)), dtype="float32")
    y = nd.array(rng.randint(0, vocab, (bptt * batch,)), dtype="float32")
    model.finalize_shapes(x)  # no-op: RNNModel declares every dim
    # bf16 weights/activations (BENCH_LSTM_DTYPE=float32 reverts): the r4
    # 740k tok/s was measured in f32 — the same dtype-audit sweep that
    # caught BERT found the LSTM/SSD legs never cast.  Cell state runs in
    # the compute dtype over bptt=35 (a 120-step CPU A/B tracked f32 to
    # within 0.03 nats); the A100 comparator ballpark is derived at bf16
    # peak, so f32 here was comparator-unfair to us.
    ldt, lmp = _bench_dtype("BENCH_LSTM_DTYPE", smoke)
    if ldt != "float32":
        model.cast(ldt)
    opt = mx.optimizer.create("sgd", learning_rate=1.0,
                              multi_precision=lmp)
    step = CompiledTrainStep(model, FlatCE(), opt)
    log("lstm: compiling full train step (first call)...")
    tok_s = _run_timed(lambda: step.step(x, y), _fetch_loss, warmup, iters,
                       repeats, batch * bptt, "lstm")
    rec = {
        "metric": "lstm_ptb_train_tokens_per_sec_per_chip"
        if not smoke else "lstm_smoke_tokens_per_sec",
        "value": round(tok_s, 2), "unit": "tok/s",
        "vs_baseline": None if smoke else round(tok_s / A100_LSTM_PTB, 4),
        "baseline_note": None if smoke else
        "derived ballpark (BASELINE.md): FLOPs model @ 20% A100 util",
        "batch": batch, "bptt": bptt, "hidden": hid, "layers": layers,
        "iters": iters, "dtype": ldt,
    }
    return rec if smoke else _strip_short_run_baseline(rec, "lstm")


def bench_ssd(smoke):
    # 128-first: the r4 third-session on-chip sweep measured
    # 32 -> 186.5, 64 -> 282.2, 128 -> 485.2 img/s, 256 -> OOM —
    # per-step fixed cost (anchor/target gen, many small heads)
    # dominated the old batch-32 default.  128 is one doubling from the
    # OOM point, so the ladder keeps the fallbacks.
    ladder = _batch_ladder("BENCH_SSD_BATCH",
                           (2,) if smoke else (128, 64, 32))
    return _run_ladder("ssd", ladder, lambda b: _ssd_once(smoke, b))


def _ssd_once(smoke, batch):
    """SSD-512 detection training (BASELINE workload 5): anchors +
    MultiBoxTarget matching with hard negative mining + CE/smooth-L1,
    all inside ONE compiled train step (target generation included, under
    stop_gradient — the reference runs it in the data/aux path).
    The official row runs the REFERENCE backbone (vgg16_reduced, the
    symbol_factory 'vgg16_reduced' 512 config) so the derived A100
    comparator in BASELINE.md applies; BENCH_SSD_BACKBONE=compact keeps
    the r4 light-backbone configuration (vs_baseline null there — no
    defensible comparator for a custom backbone)."""
    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.block import HybridBlock
    from tpu_mx.models.ssd import SSD, SSDTrainingTargets, ssd_512
    from tpu_mx.parallel import CompiledTrainStep

    backbone = os.environ.get("BENCH_SSD_BACKBONE", "vgg16_reduced")
    if smoke:
        size, classes = 64, 3
        warmup, iters, repeats = 1, 2, 1
        net = SSD(classes, sizes=[[0.2, 0.35], [0.5, 0.7]],
                  ratios=[[1, 2, 0.5]] * 2, base_filters=(8, 16))
    else:
        size, classes = 512, 20
        warmup, iters, repeats = 3, 10, 3
        net = ssd_512(classes, backbone=backbone)
    iters = int(os.environ.get("BENCH_ITERS", iters))
    targets = SSDTrainingTargets()

    class SSDTrain(HybridBlock):
        """forward(x, labels) -> per-sample loss (the tuple outputs of
        SSD can't ride through the step's single-output contract, so the
        loss lives in the forward; the step's loss_fn is a pass-through
        mean).  Head outputs upcast to f32 before target-matching and the
        losses — box/matching math is threshold-sensitive; the backbone
        compute stays in the net's dtype."""

        def __init__(self, ssd_net, **kw):
            super().__init__(**kw)
            self.net = ssd_net
            self._cls = gluon.loss.SoftmaxCrossEntropyLoss()
            self._box = gluon.loss.HuberLoss()

        def forward(self, x, labels):
            from tpu_mx import autograd, nd as _nd
            anchors, cls_preds, box_preds = self.net(x)
            anchors = _nd.cast(anchors, "float32")
            cls_preds = _nd.cast(cls_preds, "float32")
            box_preds = _nd.cast(box_preds, "float32")
            with autograd.pause():
                loc_t, loc_m, cls_t = targets(anchors, labels, cls_preds)
            return self._cls(cls_preds, cls_t) + \
                self._box(box_preds * loc_m, loc_t * loc_m)

    sdt, smp = _bench_dtype("BENCH_SSD_DTYPE", smoke)
    log(f"building ssd (size={size}, classes={classes}, backbone="
        f"{'compact' if smoke else backbone}, dtype={sdt}), batch={batch}")
    wrapper = SSDTrain(net)
    wrapper.initialize(init="xavier")
    rng = np.random.RandomState(0)
    labels = np.full((batch, 2, 5), -1.0, np.float32)
    for b in range(batch):
        cls = rng.randint(0, classes)
        x0, y0 = rng.uniform(0.05, 0.5, 2)
        x1, y1 = min(x0 + 0.3, 0.95), min(y0 + 0.3, 0.95)
        labels[b, 0] = [cls, x0, y0, x1, y1]
    # images on device (the full-batch host tensor was ~100 MB of tunnel
    # transfer — see the resnet leg note); structured labels stay host-built
    x_nd = nd.random.uniform(high=0.1, shape=(batch, 3, size, size))
    l_nd = nd.array(labels)
    wrapper.finalize_shapes(x_nd[:2], l_nd[:2])  # tiny on-device batch
    # bf16 backbone compute (BENCH_SSD_DTYPE=float32 reverts): r4's 485
    # img/s was measured in f32 — see the lstm note; heads/targets/losses
    # run f32 via the SSDTrain casts above
    if sdt != "float32":
        wrapper.cast(sdt)
        x_nd = nd.cast(x_nd, sdt)
    dummy = nd.array(np.zeros((1,), np.float32))
    opt = mx.optimizer.create("sgd", learning_rate=0.01, momentum=0.9,
                              wd=5e-4, multi_precision=smp)
    step = CompiledTrainStep(wrapper, gluon.loss.PassThrough(), opt)
    log("ssd: compiling full train step (first call)...")
    img_s = _run_timed(lambda: step.step(x_nd, l_nd, dummy), _fetch_loss,
                       warmup, iters, repeats, batch, "ssd")
    vsb = None
    note = None
    if smoke:
        metric = "ssd_smoke_images_per_sec"
    elif backbone == "vgg16_reduced":
        # the official row: reference backbone, comparator applies
        metric = "ssd512_train_images_per_sec_per_chip"
        vsb = round(img_s / A100_SSD512_VGG, 4)
        note = ("derived ballpark (BASELINE.md): NGC SSD300-RN50 "
                "utilization transferred to the VGG16-reduced SSD-512 "
                "FLOPs model")
    else:
        # a different workload gets a different key: the r4 compact
        # number must never be confusable with the vgg reference row
        metric = f"ssd512_{backbone}_train_images_per_sec_per_chip"
    rec = {
        "metric": metric,
        "value": round(img_s, 2), "unit": "img/s", "vs_baseline": vsb,
        "baseline_note": note,
        "batch": batch, "size": size,
        "backbone": "compact(smoke)" if smoke else backbone,
        "iters": iters, "dtype": sdt,
    }
    return rec if smoke else _strip_short_run_baseline(rec, "ssd")


def bench_fusion(smoke):
    """Imperative pointwise-chain microbench: the engine.bulk() lazy
    fusion engine's A/B receipts, fused and eager arms in the SAME run
    (ISSUE 1 acceptance).  Dispatch-overhead regime by design — a 32-op
    elementwise chain on a small array, where the reference's engine
    bulking (and ours) pays: the eager arm pays 32 Python+jnp dispatches
    and materializes 31 intermediates, the fused arm pays 32 lazy appends
    plus ONE memoized jitted program.  CPU is the official platform
    (JAX_PLATFORMS=cpu): on-chip numbers are dominated by the async
    dispatch queue, not the imperative overhead this measures."""
    import numpy as np
    import jax
    from tpu_mx import engine, fusion, nd

    chain_ops = 32
    shape = (64, 64)
    iters = 30 if smoke else 200
    repeats = 2 if smoke else 3
    x = nd.array(np.random.RandomState(0).rand(*shape).astype(np.float32))

    def chain(v):
        y = v
        for _ in range(chain_ops // 4):
            y = nd.sin(y)
            y = y * 1.0009
            y = y + 0.1
            y = nd.tanh(y)
        return y

    def run_arm(bulked, n):
        if bulked:
            for _ in range(n):
                with engine.bulk(chain_ops * 2):
                    chain(x).wait_to_read()
        else:
            for _ in range(n):
                chain(x).wait_to_read()

    # the eager arm must be REAL eager even if the driver exported
    # TPUMX_FUSION=1; the fused arm must fuse even under TPUMX_FUSION=0
    prior = os.environ.pop("TPUMX_FUSION", None)
    try:
        log(f"fusion: warming both arms ({chain_ops}-op chain, {shape})")
        run_arm(False, 2)
        run_arm(True, 2)  # compiles + caches the fused program
        eager = fused = None
        for r in range(repeats):
            t0 = time.perf_counter()
            run_arm(False, iters)
            e = (time.perf_counter() - t0) / iters
            t0 = time.perf_counter()
            run_arm(True, iters)
            f = (time.perf_counter() - t0) / iters
            log(f"  fusion repeat {r}: eager {e * 1e6:.0f}us "
                f"fused {f * 1e6:.0f}us ({e / f:.2f}x)")
            eager = e if eager is None else min(eager, e)
            fused = f if fused is None else min(fused, f)
    finally:
        if prior is not None:
            os.environ["TPUMX_FUSION"] = prior
    return {
        "metric": "imperative_pointwise_fusion_speedup"
        if not smoke else "imperative_fusion_smoke_speedup",
        "value": round(eager / fused, 3),
        "unit": "x",
        "vs_baseline": None,
        "eager_us_per_chain": round(eager * 1e6, 1),
        "fused_us_per_chain": round(fused * 1e6, 1),
        "chain_ops": chain_ops,
        "shape": list(shape),
        "iters": iters,
        "platform": jax.devices()[0].platform,
        # the public accessor (telemetry-backed): compiled-program count +
        # hit/miss totals persist with every benchmark receipt
        "fusion_cache": fusion.cache_stats(),
    }


def measure_decode_micro(contexts, block_size=16, batch=4, heads=4,
                         dim=16, seed=20260804, repeats=2, tq=1):
    """decode_attention micro-arm (ISSUE 9): one decode step's attention,
    paged arm (device-resident pool + block-table kernel/XLA twin) vs
    the dense-gather reference arm (host pool + padded host gather), at
    several context lengths.

    Each arm gets its OWN cache in its production storage mode, filled
    with identical fixed-seed K/V, so the A/B is the real data-plane
    swap and not a storage-mode hybrid.  Per-context receipt: per-call
    and per-sequence-token µs for both arms, min of ``repeats`` means
    (the standard min-of-repeats discipline).  Shared by the bench serve
    leg and tools/paged_sweep.py.

    ``tq > 1`` measures the WIDENED query window (ISSUE 16): the
    speculative verify call batches ``tq`` query positions per sequence
    into one attention step, so the per-TOKEN cost should amortize —
    ``*_us_per_tok`` is the comparable unit across Tq values."""
    import numpy as np
    from tpu_mx.serving import attention as _sattn
    from tpu_mx.serving.kv_cache import PagedKVCache

    rng = np.random.RandomState(seed)
    rows = []
    for ctx in contexts:
        nblocks = batch * (-(-int(ctx) // block_size)) + 8
        caches = {
            "dense": PagedKVCache(1, heads, dim, block_size=block_size,
                                  num_blocks=nblocks, storage="host"),
            "paged": PagedKVCache(1, heads, dim, block_size=block_size,
                                  num_blocks=nblocks, storage="device"),
        }
        ids = [f"s{i}" for i in range(batch)]
        for i in range(batch):
            k = rng.rand(1, ctx, heads, dim).astype(np.float32)
            v = rng.rand(1, ctx, heads, dim).astype(np.float32)
            for cache in caches.values():
                cache.prefill(ids[i], k, v)
        q = rng.rand(batch, tq, heads, dim).astype(np.float32) if tq > 1 \
            else rng.rand(batch, heads, dim).astype(np.float32)
        iters = max(8, min(64, (1 << 18) // int(ctx)))
        row = {"context": int(ctx), "batch": batch, "heads": heads,
               "dim": dim, "block_size": block_size, "tq": int(tq),
               "iters": iters}
        for kind, cache in caches.items():
            fn = lambda: _sattn.decode_attention(q, cache, ids, 0,
                                                 kind=kind)
            fn()                       # warm (jit compile / first-touch)
            best = None
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = fn()
                dt = (time.perf_counter() - t0) / iters
                best = dt if best is None else min(best, dt)
            assert np.all(np.isfinite(out))
            row[f"{kind}_us_per_call"] = round(best * 1e6, 1)
            row[f"{kind}_us_per_seq"] = round(best * 1e6 / batch, 2)
            row[f"{kind}_us_per_tok"] = round(
                best * 1e6 / (batch * tq), 2)
        row["paged_speedup"] = round(
            row["dense_us_per_call"] / row["paged_us_per_call"], 3)
        rows.append(row)
        log(f"  decode micro ctx={ctx} tq={tq}: dense "
            f"{row['dense_us_per_call']}us paged "
            f"{row['paged_us_per_call']}us "
            f"({row['paged_speedup']}x)")
    return rows


def measure_prefix_trace(model, smoke, seed):
    """Shared-prefix heavy-tail trace (ISSUE 12): N tenants drawing
    prompts from K templates — the "millions of users on shared system
    prompts" regime — run through the Server with prefix sharing ON vs
    OFF, in BOTH decode modes, on the SAME fixed-seed trace.

    Receipts per mode: ``prefix_hit_ratio`` (cached / total prompt
    tokens), ``prefill_bytes`` per arm and the off/on reduction ratio
    (acceptance bar: >= 2x), wall-clock per arm, and the hard gate —
    greedy token streams BIT-identical between arms (sharing must be a
    pure storage/compute optimization, never a behavior change; the
    suffix prefill reproduces the full prefill's logits exactly,
    tests/test_multitenant.py).  Each arm ends with the allocator
    refcount audit: drop the index, assert every refcount returned to
    zero."""
    import numpy as np
    from tpu_mx import serving

    rng = np.random.RandomState(seed + 12)
    n_req = 16 if smoke else 48
    tenants = ["t0", "t1", "t2", "t3"]
    # 48-token templates = 3 full 16-blocks shareable per prompt; the
    # 2-6 token unique tails model per-user payloads on a shared prompt
    templates = [list(1 + rng.randint(0, 120, size=48)) for _ in range(4)]
    choices = rng.randint(0, len(templates), size=n_req)
    tails = [list(1 + rng.randint(0, 120, size=int(t)))
             for t in rng.randint(2, 7, size=n_req)]
    # heavy-tailed generation lengths, like the main serve trace
    outs = [int(v) for v in rng.choice([4, 8, 16, 64], size=n_req,
                                       p=[0.35, 0.30, 0.20, 0.15])]
    assign = [tenants[i % len(tenants)] for i in range(n_req)]

    def arm(share, mode):
        prior = os.environ.get("TPUMX_PAGED_DECODE")
        os.environ["TPUMX_PAGED_DECODE"] = mode
        try:
            srv = serving.Server(
                model, num_blocks=4096, block_size=16, max_batch=16,
                max_pending=n_req + 1, max_tokens=10 ** 9,
                prefix_sharing=share,
                tenants={t: {"weight": 1.0} for t in tenants})
            t0 = time.perf_counter()
            reqs = [srv.submit(templates[c] + tails[i],
                               max_new_tokens=outs[i], tenant=assign[i])
                    for i, c in enumerate(choices)]
            srv.run_until_idle()
            wall = time.perf_counter() - t0
            stats = srv.engine.cache.prefix_stats()
            # post-run allocator audit: every reference returns to zero
            srv.engine.cache.drop_prefix_cache()
            leftover = srv.engine.cache.allocator.refcounts()
            assert not leftover, f"refcount leak after trace: {leftover}"
            return [r.tokens for r in reqs], stats, wall
        finally:
            if prior is None:
                os.environ.pop("TPUMX_PAGED_DECODE", None)
            else:
                os.environ["TPUMX_PAGED_DECODE"] = prior

    rows = {}
    for mode, tag in (("0", "dense"), ("1", "paged")):
        on_streams, on, w_on = arm(True, mode)
        off_streams, off, w_off = arm(False, mode)
        assert on_streams == off_streams, (
            f"greedy streams diverged with sharing on ({tag} mode) — "
            "sharing must be invisible to outputs")
        ratio = off["prefill_bytes"] / max(on["prefill_bytes"], 1)
        assert on["hit_ratio"] > 0, on
        assert ratio >= 2.0, (
            f"prefill-bytes reduction {ratio:.2f}x < 2x bar ({tag})")
        rows[tag] = {
            "prefix_hit_ratio": round(on["hit_ratio"], 4),
            "prefill_bytes_sharing_on": on["prefill_bytes"],
            "prefill_bytes_sharing_off": off["prefill_bytes"],
            "prefill_bytes_reduction": round(ratio, 2),
            "prefill_bytes_saved": on["prefill_bytes_saved"],
            "index_nodes_peak": on.get("nodes", 0),
            "streams_identical": True,
            "wall_s_sharing_on": round(w_on, 3),
            "wall_s_sharing_off": round(w_off, 3),
        }
        log(f"serve: prefix trace [{tag}] hit_ratio "
            f"{rows[tag]['prefix_hit_ratio']} prefill bytes "
            f"{off['prefill_bytes']} -> {on['prefill_bytes']} "
            f"({ratio:.1f}x), streams identical")
    record = {"n_requests": n_req, "templates": len(templates),
              "tenants": len(tenants), "trace_seed": seed + 12,
              "block_size": 16, "modes": rows}
    # persist the receipt per the artifact protocol (merge-on-write,
    # atomic) alongside the BENCH record that also embeds it
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from artifact_protocol import artifact, load_prior, write_atomic
        path = artifact("PREFIX_TRACE")
        prior = load_prior(path)
        merged_modes = dict(prior.get("modes", {}))
        merged_modes.update(rows)
        out = dict(record, modes=merged_modes, platform="host")
        write_atomic(path, out)
        log(f"serve: prefix-trace receipt -> {path}")
    except Exception as e:  # noqa: BLE001 — receipt persistence is
        log(f"serve: prefix-trace artifact write skipped: {e}")  # best-effort
    return record


def measure_fused_micro(model, smoke, block_size=16, batch=8, ctx=48,
                        seed=20260804):
    """Fused whole-step vs host-resident decode forward (ISSUE 16): the
    per-decode-step A/B at standard-trace shapes.  Both arms run the
    SAME paged engine config and the SAME prefilled batch; only the
    step dispatch differs — the host arm's per-layer numpy/attention
    interleave (O(layers) host<->device crossings) vs the one jitted
    device program (constant 3).  Two fresh-engine passes per arm, the
    first discarded: it compiles every table-width bucket the
    generation crosses, so the timed pass measures steady-state decode
    and not XLA compiles (min-of-passes would hide, not amortize, a
    mid-pass compile).  Receipt unit: per-TOKEN µs — the acceptance bar
    (fused >= 1.5x) is gated here, where the decode forward is isolated
    from the trace's shared prefill/scheduler/telemetry overhead."""
    import numpy as np
    from tpu_mx.serving.engine import EngineCore

    steps = 24 if smoke else 48
    rng = np.random.RandomState(seed)
    prompts = [list(1 + rng.randint(0, 120, size=ctx))
               for _ in range(batch)]

    class _Req:
        def __init__(self, i, prompt):
            self.id = f"fm{i}"
            self.prompt = prompt

    def arm(fused):
        prior = {k: os.environ.get(k)
                 for k in ("TPUMX_PAGED_DECODE", "TPUMX_FUSED_DECODE")}
        # both arms on the PAGED engine: the fused program needs the
        # device-resident pool, and the host arm must be the same
        # data plane for the A/B to isolate the step dispatch
        os.environ["TPUMX_PAGED_DECODE"] = "1"
        os.environ["TPUMX_FUSED_DECODE"] = fused
        try:
            best = None
            for timed in (False, True):
                eng = EngineCore(model, block_size=block_size,
                                 num_blocks=2048,
                                 warm_batch=batch if fused == "1"
                                 else None)
                items = []
                for i, p in enumerate(prompts):
                    req = _Req(i, p)
                    tok, _ = eng.prefill(req)
                    items.append((req, tok))
                t0 = time.perf_counter()
                for _ in range(steps):
                    results, _ = eng.decode(items)
                    items = [(r, results[r.id][-1]) for r, _ in items]
                dt = time.perf_counter() - t0
                if timed:
                    best = dt / steps
            return best
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    host = arm("0")
    fused = arm("1")
    row = {"batch": batch, "context": ctx, "steps": steps,
           "block_size": block_size,
           "host_us_per_step": round(host * 1e6, 1),
           "fused_us_per_step": round(fused * 1e6, 1),
           "host_us_per_tok": round(host * 1e6 / batch, 2),
           "fused_us_per_tok": round(fused * 1e6 / batch, 2),
           "fused_decode_speedup": round(host / fused, 3)}
    log(f"  fused micro: host {row['host_us_per_tok']}us/tok fused "
        f"{row['fused_us_per_tok']}us/tok "
        f"({row['fused_decode_speedup']}x)")
    assert row["fused_decode_speedup"] >= 1.5, (
        f"fused whole-step decode only {row['fused_decode_speedup']}x "
        "over the host-resident forward (acceptance bar 1.5x) — the "
        "one-device-program win regressed")
    return row


def bench_serve(smoke):
    """Serving A/B: continuous batching vs naive static batching over a
    synthetic heavy-traffic trace (ISSUE 8 acceptance), plus the ISSUE 9
    paged-decode receipts: the long-generation per-token-flat probe in
    BOTH decode modes and the decode_attention micro-arm (paged kernel /
    XLA twin vs dense-gather at 3+ context lengths), plus the ISSUE 12
    shared-prefix multi-tenant trace (measure_prefix_trace), plus the
    ISSUE 16 fused-step receipts: the whole-step-program vs
    host-resident-forward decode micro-arm (>= 1.5x bar gated in
    measure_fused_micro) and the fused / fused+speculative trace arms
    with accept-ratio and ITL-delta receipts (knob_arm below).

    Fixed-seed workload: Poisson arrivals (exponential inter-arrival
    gaps in engine-step units), mixed prompt lengths and heavy-tailed
    output lengths — the regime where static batching pads every slot to
    its batch's slowest member while continuous batching refills freed
    slots on the next step.  Both arms run the SAME trace through the
    SAME model/engine/cache config; only the scheduler differs
    (tpu_mx/serving/scheduler.py).  Reported: tokens/s per arm, the
    continuous/static speedup (acceptance bar: >= 2x), p50/p99 TTFT and
    ITL (exact percentiles off the per-request timestamps — the
    telemetry histograms are the production view, bucket-granular), and
    the O(1) receipt: per-token decode latency early vs late in a long
    generation (flat = the paged cache's append cost does not grow with
    generated length at this scale; the dense-gather O(context) term is
    below host overhead here, docs/serving.md)."""
    import numpy as np
    from tpu_mx import serving

    seed = 20260804
    n_req = 16 if smoke else 64
    long_gen = 64 if smoke else 256
    # 16-wide batches: wide enough that the static baseline's
    # pad-to-slowest waste is the realistic one (the wider the batch,
    # the worse the max-over-batch padding — and the better continuous
    # amortizes its fixed per-step cost)
    max_batch = 16
    rng = np.random.RandomState(seed)
    prompts = [list(1 + rng.randint(0, 120, size=int(n)))
               for n in rng.choice([8, 16, 32], size=n_req)]
    # heavy-tailed outputs: the 96-token tail is what static batching
    # pads every batch member to
    outs = [int(v) for v in rng.choice(
        [4, 8, 16, 96], size=n_req, p=[0.30, 0.30, 0.25, 0.15])]
    arrival_step = np.floor(np.cumsum(
        rng.exponential(0.5, size=n_req))).astype(int)
    model = serving.TinyLM(vocab_size=128, embed_dim=64, num_heads=4,
                           num_layers=2, seed=0)

    def pct(vals, q):
        return float(np.percentile(np.asarray(vals, np.float64), q))

    # the live-vs-exact bar (ISSUE 11): the SLO engine's windowed
    # bucket-merge estimates must track the exact offline percentiles —
    # the standing proof the "p99 right now" numbers a dashboard reads
    # can be trusted.  Smoke's 16-request percentiles are rank-noisy
    # (p99 of 16 samples rides the top order statistic), so the bar
    # loosens there; the full leg holds 10%.  The bar is ASSERTED on
    # the continuous arm (the production policy whose TTFT/ITL are the
    # leg's SLO receipts); the static strawman's deltas are recorded
    # but not gated — its batch-drain TTFT clusters are a point-mass
    # distribution where within-bucket interpolation can drift past
    # 10% at p50, a shape the intentionally-bad baseline manufactures.
    slo_rel_tol = 0.15 if smoke else 0.10

    def run_arm(sched_cls, assert_live=True):
        from tpu_mx import telemetry as _tel
        # reset each SLO histogram's window ring with a horizon covering
        # the whole arm, so the live estimate aggregates exactly this
        # arm's samples (cumulative state is untouched)
        _tel.histogram("serve.ttft_seconds").configure_window(600.0, 12)
        _tel.histogram("serve.itl_seconds").configure_window(600.0, 12)
        srv = serving.Server(
            model, scheduler=sched_cls(max_pending=n_req + 1,
                                       max_batch=max_batch,
                                       max_tokens=10 ** 9),
            num_blocks=4096, block_size=16)
        reqs, i, step = [], 0, 0
        # capacity receipts (ISSUE 14): per-step pool-bytes samples (one
        # O(1) counter read per step) for the steady-state figure; the
        # peak comes from the ledger's own high watermark after the arm
        cache = srv.engine.cache
        block_bytes = cache.allocator.ledger.block_bytes
        pool_samples = []
        t0 = time.perf_counter()
        while i < n_req or not srv.scheduler.idle():
            while i < n_req and arrival_step[i] <= step:
                reqs.append(srv.submit(prompts[i], max_new_tokens=outs[i]))
                i += 1
            srv.step()
            step += 1
            pool_samples.append(cache.allocator.used * block_bytes)
        wall = time.perf_counter() - t0
        cap = cache.capacity_stats()
        busy = [s for s in pool_samples if s > 0] or [0]
        pool = {"pool_peak_bytes": int(cap["high_watermark_bytes"]),
                "pool_steady_bytes": int(np.median(busy)),
                "pool_end_fragmentation": round(cap["fragmentation"], 4),
                "pool_block_bytes": int(block_bytes)}
        total = sum(len(r.tokens) for r in reqs)
        assert total == sum(outs), "lost tokens"
        # the live-vs-exact comparison below is only apples-to-apples
        # when no request was requeued: reset_generation clears the
        # token_times the exact list is built from, but the discarded
        # attempt's observations stay in the window ring.  The fixed
        # trace never preempts today — make that a loud precondition
        # rather than a confusing estimator-drift failure if the trace
        # or pool sizing is ever retuned.
        assert not any(r.requeues for r in reqs), (
            "bench arm saw requeues; live-vs-exact gate precondition "
            "broken — retune the trace or pool sizing")
        ttft = [r.ttft * 1e3 for r in reqs]
        itl = [dt * 1e3
               for r in reqs
               for dt in np.diff(r.token_times)] or [0.0]
        exact = {"ttft_ms_p50": round(pct(ttft, 50), 2),
                 "ttft_ms_p99": round(pct(ttft, 99), 2),
                 "itl_ms_p50": round(pct(itl, 50), 3),
                 "itl_ms_p99": round(pct(itl, 99), 3)}
        # The runtime SLO engine's windowed estimates next to the exact
        # offline percentiles.  GATED against the order-statistic
        # BRACKET [percentile(method=lower), percentile(method=higher)]:
        # a p99 of 64 requests rides the gap between the top two order
        # statistics, where the "exact" value is itself a convention
        # (linear/lower/higher disagree by the whole gap) — the bucket
        # estimate is guaranteed within one ~5% bucket of that bracket,
        # so the 10% bar is meaningful rather than rank-lottery.  The
        # linear-convention delta is reported alongside for the receipt.
        live, rel_errs, bracket_errs = {}, {}, {}
        for name, key, samples in (
                ("serve.ttft_seconds", "ttft_ms", ttft),
                ("serve.itl_seconds", "itl_ms", itl)):
            h = _tel.get(name)
            arr = np.asarray(samples, np.float64)
            for q, qtag in ((0.50, "p50"), (0.99, "p99")):
                est = h.window_quantile(q)
                assert est is not None, (name, "empty SLO window")
                est_ms = est * 1e3
                live[f"{key}_{qtag}"] = round(est_ms, 3)
                ex = exact[f"{key}_{qtag}"]
                rel_errs[f"{key}_{qtag}"] = round(
                    abs(est_ms - ex) / max(ex, 1e-9), 4)
                lo = float(np.percentile(arr, q * 100, method="lower"))
                hi = float(np.percentile(arr, q * 100, method="higher"))
                gap = max(lo - est_ms, est_ms - hi, 0.0)
                bracket_errs[f"{key}_{qtag}"] = round(
                    gap / max(ex, 1e-9), 4)
        worst = max(bracket_errs.values())
        assert not assert_live or worst <= slo_rel_tol, (
            f"live SLO estimate drifted {worst:.1%} outside the exact "
            f"order-statistic bracket (bar {slo_rel_tol:.0%}): "
            f"live={live} exact={exact}")
        return dict(exact, tokens_per_sec=round(total / wall, 1),
                    steps=step, wall_s=round(wall, 3),
                    slo_live=live, slo_live_rel_err=rel_errs,
                    slo_live_bracket_err=bracket_errs, **pool)

    # warm both code paths before timing either arm: the first prefill/
    # decode at each shape pays one-time numpy/dispatch setup (measured
    # ~6ms vs ~0.8ms for an L=32 prefill) that would otherwise be billed
    # entirely to whichever arm runs first — same discipline as the
    # fusion leg's dual-arm warmup
    wsrv = serving.Server(model, num_blocks=4096, block_size=16,
                          max_batch=max_batch)
    for p in ([8, 9] * 4, [8, 9] * 8, [8, 9] * 16):
        wsrv.submit(list(p), max_new_tokens=8)
    wsrv.run_until_idle()

    log(f"serve: {n_req}-request Poisson trace, continuous arm...")
    cont = run_arm(serving.ContinuousBatchingScheduler)
    log(f"  continuous: {cont['tokens_per_sec']} tok/s in "
        f"{cont['steps']} steps; ttft p50/p99 "
        f"{cont['ttft_ms_p50']}/{cont['ttft_ms_p99']} ms")
    log(f"  live SLO estimates: {cont['slo_live']} (vs exact-linear "
        f"worst {max(cont['slo_live_rel_err'].values()):.1%}; vs "
        f"order-statistic bracket worst "
        f"{max(cont['slo_live_bracket_err'].values()):.1%}, gated at "
        f"{slo_rel_tol:.0%})")
    log(f"  pool: peak {cont['pool_peak_bytes']} B, steady "
        f"{cont['pool_steady_bytes']} B, end fragmentation "
        f"{cont['pool_end_fragmentation']}")
    log("serve: static arm...")
    stat = run_arm(serving.StaticBatchingScheduler, assert_live=False)
    log(f"  static:     {stat['tokens_per_sec']} tok/s in "
        f"{stat['steps']} steps")
    speedup = cont["tokens_per_sec"] / max(stat["tokens_per_sec"], 1e-9)

    # O(1) receipt, BOTH decode modes: one long generation, ITL early vs
    # late.  The paged append is O(1); the dense arm additionally pays
    # the O(context) host gather, the paged arm only the in-program
    # block walk.  Two probe runs, window MEDIANS, min-of-pairs: a
    # single preempted-by-the-OS token (or one noisy run — or, on the
    # paged arm, a block-bucket jit compile) would otherwise fake or
    # hide growth — same min-of-repeats discipline as the other legs
    def flat_probe(mode):
        prior = os.environ.get("TPUMX_PAGED_DECODE")
        os.environ["TPUMX_PAGED_DECODE"] = mode
        try:
            early = late = None
            for _ in range(2):
                srv = serving.Server(model, num_blocks=4096,
                                     block_size=16)
                lr = srv.submit(prompts[0], max_new_tokens=long_gen)
                srv.run_until_idle()
                d = np.diff(lr.token_times) * 1e6
                e = float(np.median(d[8:40]))
                l = float(np.median(d[-32:]))
                early = e if early is None else min(early, e)
                late = l if late is None else min(late, l)
            return early, late
        finally:
            if prior is None:
                os.environ.pop("TPUMX_PAGED_DECODE", None)
            else:
                os.environ["TPUMX_PAGED_DECODE"] = prior

    early, late = flat_probe("0")
    log(f"serve: dense per-token decode early {early:.0f}us late "
        f"{late:.0f}us (x{late / early:.2f} over {long_gen} tokens)")
    pearly, plate = flat_probe("1")
    log(f"serve: paged per-token decode early {pearly:.0f}us late "
        f"{plate:.0f}us (x{plate / pearly:.2f} over {long_gen} tokens)")

    # decode_attention micro-arm: the data-plane A/B at fixed contexts
    micro = measure_decode_micro((64, 128, 256) if smoke
                                 else (128, 512, 2048))

    # ISSUE 16 receipts.  (1) The fused whole-step micro-arm: the
    # >= 1.5x acceptance bar is gated inside (per-token decode at
    # standard-trace shapes — decode isolated from shared overhead).
    fused_micro = measure_fused_micro(model, smoke, seed=seed)

    # (2) Trace-level arms on the SAME standard trace, paged engine:
    # host-resident forward vs fused program vs fused+speculative.
    # Each arm runs once discarded (compiles every batch/table-width
    # bucket the trace crosses) then once timed — steady-state serving,
    # the regime the tokens/sec receipt describes.  run_arm's live-SLO
    # bracket gate rides along, so the speculative arm's windowed
    # p50/p99 estimates are asserted within the 10% bar of
    # offline-exact (the ISSUE 16 acceptance wording).
    def knob_arm(fused, spec):
        from tpu_mx import telemetry as _tel
        prior = {k: os.environ.get(k)
                 for k in ("TPUMX_PAGED_DECODE", "TPUMX_FUSED_DECODE",
                           "TPUMX_SPECULATIVE")}
        os.environ["TPUMX_PAGED_DECODE"] = "1"
        os.environ["TPUMX_FUSED_DECODE"] = fused
        os.environ["TPUMX_SPECULATIVE"] = spec
        try:
            run_arm(serving.ContinuousBatchingScheduler,
                    assert_live=False)       # discarded: compile pass
            c0 = {n: getattr(_tel.get(n), "value", 0) or 0
                  for n in ("serve.spec_drafted", "serve.spec_accepted")}
            rec = run_arm(serving.ContinuousBatchingScheduler)
            drafted = (getattr(_tel.get("serve.spec_drafted"), "value",
                               0) or 0) - c0["serve.spec_drafted"]
            accepted = (getattr(_tel.get("serve.spec_accepted"), "value",
                                0) or 0) - c0["serve.spec_accepted"]
            rec["spec_drafted"] = int(drafted)
            rec["spec_accepted"] = int(accepted)
            rec["spec_accept_ratio"] = round(accepted / drafted, 4) \
                if drafted else None
            return rec
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    host_arm = knob_arm("0", "0")
    fused_arm = knob_arm("1", "0")
    spec_arm = knob_arm("1", "1")
    fused_trace_speedup = round(fused_arm["tokens_per_sec"]
                                / host_arm["tokens_per_sec"], 3)
    log(f"  fused trace arms (paged): host "
        f"{host_arm['tokens_per_sec']} tok/s, fused "
        f"{fused_arm['tokens_per_sec']} tok/s "
        f"({fused_trace_speedup}x end-to-end), fused+spec "
        f"{spec_arm['tokens_per_sec']} tok/s (accept ratio "
        f"{spec_arm['spec_accept_ratio']})")

    # shared-prefix multi-tenant trace (ISSUE 12): hit-ratio +
    # prefill-bytes receipts, sharing on/off, both decode modes,
    # streams gated bit-identical
    prefix = measure_prefix_trace(model, smoke, seed)

    return {
        "metric": "serve_continuous_tokens_per_sec"
        if not smoke else "serve_smoke_tokens_per_sec",
        "value": cont["tokens_per_sec"],
        "unit": "tok/s",
        "vs_baseline": None,
        "speedup_vs_static": round(speedup, 2),
        "continuous": cont,
        "static": stat,
        # live-vs-exact proof (ISSUE 11): the SLO engine's windowed
        # p50/p99 next to the offline-exact percentiles, per arm (the
        # per-metric deltas ride each arm's slo_live_rel_err /
        # slo_live_bracket_err; the assert in run_arm gates the
        # continuous arm's bracket distance — the static strawman's
        # deltas are recorded unasserted, see the comment above run_arm)
        "slo_live_max_rel_err": round(
            max(cont["slo_live_rel_err"].values()), 4),
        "slo_live_max_bracket_err": round(
            max(cont["slo_live_bracket_err"].values()), 4),
        "slo_live_rel_tol": slo_rel_tol,
        # capacity receipts (ISSUE 14), flat so the artifact trajectory
        # diffs them directly: the continuous arm's ledger high
        # watermark, the median nonzero pool residency, and end-state
        # free-list fragmentation — a future capacity regression (a
        # leak, a sharing break, a fragmentation explosion) moves these
        # before it moves tokens/sec
        "pool_peak_bytes": cont["pool_peak_bytes"],
        "pool_steady_bytes": cont["pool_steady_bytes"],
        "pool_end_fragmentation": cont["pool_end_fragmentation"],
        "pool_block_bytes": cont["pool_block_bytes"],
        # O(1)-append receipt.  A cache-less (recompute-the-prefix)
        # decode's per-token cost scales ~linearly with context —
        # "linear_would_be" is the late/early CONTEXT ratio such a decode
        # would show; the small measured residual is the documented
        # dense-gather O(context) fallback term (docs/DIVERGENCES.md
        # #27) riding on an O(1) paged append.
        "per_token_flat": {"early_itl_us": round(early, 1),
                           "late_itl_us": round(late, 1),
                           "late_over_early": round(late / early, 3),
                           "generated": long_gen,
                           "linear_would_be": round(
                               (len(prompts[0]) + long_gen - 16)
                               / (len(prompts[0]) + 24), 1)},
        # the same receipt on the paged decode path (TPUMX_PAGED_DECODE=1,
        # device-resident pool): acceptance bar late/early <= 1.15 over
        # the same >=4x context growth (ISSUE 9)
        "per_token_flat_paged": {"early_itl_us": round(pearly, 1),
                                 "late_itl_us": round(plate, 1),
                                 "late_over_early": round(plate / pearly,
                                                          3)},
        # decode_attention micro-arm: paged (device pool, block-table
        # program) vs dense-gather (host pool) per decode step at fixed
        # contexts — the bar is paged winning at the LONGEST context
        "decode_micro": micro,
        # ISSUE 16 fused-step receipts, flat so the trajectory diffs
        # them: the >= 1.5x bar lives on the DECODE micro-arm (gated in
        # measure_fused_micro — the whole-step program vs the O(layers)
        # host forward, isolated from shared trace overhead); the
        # end-to-end trace ratio is reported honestly unasserted (the
        # tiny model's prefill/scheduler/telemetry share dilutes it)
        "fused_us_per_tok": fused_micro["fused_us_per_tok"],
        "host_resident_us_per_tok": fused_micro["host_us_per_tok"],
        "fused_decode_speedup": fused_micro["fused_decode_speedup"],
        "fused_tokens_per_sec": fused_arm["tokens_per_sec"],
        "host_paged_tokens_per_sec": host_arm["tokens_per_sec"],
        "fused_trace_speedup": fused_trace_speedup,
        # speculative receipts: accept ratio + ITL deltas vs the fused
        # non-speculative arm on the same trace (negative delta = the
        # draft window bought latency); the spec arm's windowed SLO
        # estimates passed run_arm's 10% bracket gate to get here
        "spec_tokens_per_sec": spec_arm["tokens_per_sec"],
        "spec_accept_ratio": spec_arm["spec_accept_ratio"],
        "spec_drafted": spec_arm["spec_drafted"],
        "spec_accepted": spec_arm["spec_accepted"],
        "spec_itl_ms_p50": spec_arm["itl_ms_p50"],
        "spec_itl_ms_p99": spec_arm["itl_ms_p99"],
        "spec_itl_ms_p50_delta": round(
            spec_arm["itl_ms_p50"] - fused_arm["itl_ms_p50"], 3),
        "spec_itl_ms_p99_delta": round(
            spec_arm["itl_ms_p99"] - fused_arm["itl_ms_p99"], 3),
        "fused_micro": fused_micro,
        "fused_arm": fused_arm,
        "host_paged_arm": host_arm,
        "spec_arm": spec_arm,
        # shared-prefix multi-tenant receipts (ISSUE 12): hit ratio,
        # prefill-bytes reduction (bar >= 2x) and stream-equality gate
        # per decode mode; also persisted as PREFIX_TRACE_<round>.json
        "prefix_trace": prefix,
        "n_requests": n_req,
        "max_batch": max_batch,
        "trace_seed": seed,
        "model": {"vocab": model.vocab_size, "embed": model.embed_dim,
                  "heads": model.num_heads, "layers": model.num_layers},
        "platform": "host",   # numpy data plane; the dense-gather decode
                              # fallback is the measured path (#27)
    }


def bench_scaling(smoke):
    """Weak-scaling efficiency over all visible devices (BASELINE metric 3
    'scaling efficiency' — the full 8→256-chip number needs a pod slice;
    this harness measures whatever mesh the process sees, e.g. the
    8-virtual-device CPU mesh in smoke or a real slice when available):
    throughput(dp=N, batch=N·b) / (N · throughput(dp=1, batch=b))."""
    import jax
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.layout import default_layout
    from tpu_mx.parallel import CompiledTrainStep, make_mesh

    n = len(jax.devices())
    if n == 1:
        log("scaling: only one device visible — weak scaling is trivially "
            "1.0; skipping the duplicate run (needs a pod slice)")
        return {"metric": "weak_scaling_efficiency_dp1", "value": 1.0,
                "unit": "ratio", "vs_baseline": 1.0,
                "note": "single device; measure on a multi-chip slice"}
    per_dev_batch, size, iters = (4, 32, 3) if smoke else (64, 96, 10)

    def throughput(ndev):
        batch = per_dev_batch * ndev
        with default_layout("NHWC"):
            net = vision.resnet18_v1(classes=100)
        net.initialize(init="xavier")
        net.finalize_shapes(nd.random.uniform(shape=(2, size, size, 3)))
        x = nd.random.uniform(shape=(batch, size, size, 3))
        mesh = make_mesh({"dp": ndev}, devices=jax.devices()[:ndev]) \
            if ndev > 1 else None
        opt = mx.optimizer.create("sgd", learning_rate=0.1)
        step = CompiledTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 opt, mesh=mesh)
        y = nd.random.randint(0, 100, (batch,), dtype="float32")
        _timed(lambda: step.step(x, y), _fetch_loss, 1)    # compile
        dt = _timed(lambda: step.step(x, y), _fetch_loss, iters)
        return batch * iters / dt

    t1 = throughput(1)
    tn = throughput(n)
    eff = tn / (n * t1)
    log(f"scaling: dp=1 {t1:.1f} img/s, dp={n} {tn:.1f} img/s, "
        f"efficiency {eff:.3f}")
    return {
        "metric": f"weak_scaling_efficiency_dp{n}",
        "value": round(eff, 4),
        "unit": "ratio",
        "vs_baseline": round(eff, 4),
        "throughput_dp1": round(t1, 2),
        f"throughput_dp{n}": round(tn, 2),
    }


def inner():
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")
    stem = os.environ.get("BENCH_STEM", "s2d")
    models = [m.strip() for m in
              os.environ.get("BENCH_MODELS",
                             "resnet50,bert,bert512,lstm,ssd").split(",")
              if m.strip()]
    unknown = set(models) - {"resnet50", "bert", "bert512", "scaling",
                             "lstm", "ssd", "fusion", "serve"}
    if unknown or not models:
        raise SystemExit(f"BENCH_MODELS: unknown/empty model list {models}")
    log(f"inner start (smoke={smoke}, layout={layout}, stem={stem}, "
        f"models={models})")

    import jax
    if smoke or os.environ.get("JAX_PLATFORMS") == "cpu":
        # the environment's sitecustomize imports jax with the axon TPU
        # platform pinned BEFORE env vars can take effect, so an explicit
        # JAX_PLATFORMS=cpu (a CPU verification drive) must be honored
        # through jax.config — otherwise the drive blocks initializing
        # the tunneled backend it was explicitly avoiding
        jax.config.update("jax_platforms", "cpu")

    # persistent compile cache: a tunnel window is precious — if a run
    # dies mid-sweep, the retry must not pay the tens-of-seconds compiles
    # again (BENCH_COMPILE_CACHE=0 disables for all three on-chip tools)
    if not smoke:
        from tpu_mx.runtime import enable_shared_compilation_cache
        enable_shared_compilation_cache()

    if os.environ.get("BENCH_SIMULATE_WEDGE") == "1":
        # test hook for the outer supervisor's wedge handling: behave like
        # the round-3 tunnel (jax.devices() stuck in a C call, 'backend up'
        # never printed) without needing a broken backend
        log("probing backend (jax.devices)...")
        time.sleep(3600)

    log("probing backend (jax.devices)...")
    t0 = time.perf_counter()
    devs = jax.devices()
    log(f"backend up: {devs[0].platform} x{len(devs)} "
        f"in {time.perf_counter() - t0:.1f}s")

    log("staged warmup: tiny jit matmul...")
    import jax.numpy as jnp
    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.bfloat16)
    jax.jit(lambda a: a @ a)(x).block_until_ready()
    log(f"tiny jit ok in {time.perf_counter() - t0:.1f}s")

    # BENCH_SKIP_FRESH=<seconds>: carry a leg's stored record instead of
    # re-measuring when it is younger than this (0/unset = always measure;
    # smoke never carries).  The watcher's bench stage sets it so a retry
    # after a mid-run wedge spends the next window on the missing legs.
    try:
        skip_fresh = 0.0 if smoke else \
            float(os.environ.get("BENCH_SKIP_FRESH", "0") or 0)
    except ValueError:
        skip_fresh = 0.0

    rec = None
    if "resnet50" in models:
        # canonical-iters gate: the CURRENT run's BENCH_ITERS must not
        # lower the bar a stored record has to clear (ADVICE r5 low)
        rec = _fresh_stored(
            PRIMARY_METRIC, skip_fresh,
            min_iters=FULL_RUN_ITERS["resnet50"]) \
            if skip_fresh else None
        if rec is not None:
            log(f"resnet: carrying fresh record from {rec['measured_at']} "
                f"(BENCH_SKIP_FRESH={skip_fresh:.0f}s)")
        else:
            rec = bench_resnet(smoke, layout, stem)
        if rec is not None and not rec.get("carried_fresh"):
            # stream + persist the primary record as soon as it exists: if
            # a later sub-bench dies/hangs and the attempt is killed, the
            # measurement still survives on disk (and the outer's next
            # attempt can narrow BENCH_MODELS from the logs)
            log("resnet record: " + json.dumps(rec))
            persist_lastgood(rec)
    bert_rec = scal_rec = None
    try:
        if "bert" in models:
            bert_rec = _fresh_stored(
                "bert_base_train_seqs_per_sec_per_chip", skip_fresh) \
                if skip_fresh else None
            if bert_rec is not None:
                log(f"bert: carrying fresh record from "
                    f"{bert_rec['measured_at']} (BENCH_SKIP_FRESH)")
        if bert_rec is None:
            bert_rec = bench_bert(smoke) if "bert" in models else None
        if bert_rec is not None and not bert_rec.get("carried_fresh"):
            # persist the moment it exists (the r4 final-run lesson: a
            # later sub-bench hanging past the attempt timeout killed the
            # process before the old end-of-inner persist loop ran, and
            # the measured BERT number died with it)
            log("bert record: " + json.dumps(bert_rec))
            persist_lastgood(bert_rec)
    except Exception as e:  # keep the primary metric alive
        log(f"bert bench failed: {type(e).__name__}: {e}")
        bert_rec = {"metric": "bert_base_train_seqs_per_sec_per_chip",
                    "value": 0.0, "unit": "seq/s", "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:300]}
        if rec is None:
            raise
    try:
        scal_rec = bench_scaling(smoke) if "scaling" in models else None
        if scal_rec is not None:
            log("scaling record: " + json.dumps(scal_rec))
            persist_lastgood(scal_rec)
    except Exception as e:
        log(f"scaling bench failed: {type(e).__name__}: {e}")
        if rec is None and bert_rec is None:
            raise
        scal_rec = {"metric": "weak_scaling_efficiency", "value": 0.0,
                    "unit": "ratio", "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:300]}
    # secondary workloads (BASELINE configs 3 and 5): never fatal to the
    # primary record; persisted under their own metric keys and attached
    # to the combined record for the session log
    extra_recs = {}
    ssd_backbone = os.environ.get("BENCH_SSD_BACKBONE", "vgg16_reduced")
    extra_metrics = {
        "bert512": "bert_base_seq512_train_seqs_per_sec_per_chip",
        "lstm": "lstm_ptb_train_tokens_per_sec_per_chip",
        "fusion": "imperative_pointwise_fusion_speedup",
        "serve": "serve_continuous_tokens_per_sec",
        "ssd": "ssd512_train_images_per_sec_per_chip"
        if ssd_backbone == "vgg16_reduced"
        else f"ssd512_{ssd_backbone}_train_images_per_sec_per_chip"}

    def _bert512_complete(rec_):
        # a carried bert512 record must include the Pallas-flash receipt:
        # either the auto arm compiled flash, or a healthy pinned flash_arm
        # rode along.  The auto-arm-only record a flash-compile wedge
        # leaves behind must trigger a re-measure, not a 4h carry
        # (ADVICE r5 medium, bench.py:1083).
        if rec_.get("attention_path") == "pallas_flash":
            return True
        fa = rec_.get("flash_arm")
        return isinstance(fa, dict) and "error" not in fa and \
            isinstance(fa.get("value"), (int, float)) and fa["value"] > 0
    # bert512 deliberately runs LAST: its remat+flash compile is the
    # largest program this file builds, and on 2026-08-02 a tunnel wedge
    # inside that compile burned the rest of a 15-minute window while
    # lstm/ssd were still unmeasured — the riskiest leg must not sit in
    # front of cheap ones
    for name, fn_extra in (("fusion", bench_fusion), ("serve", bench_serve),
                           ("lstm", bench_lstm), ("ssd", bench_ssd),
                           ("bert512", bench_bert512)):
        if name not in models:
            continue
        # fusion and serve re-measure in seconds: never carry them
        if skip_fresh and name not in ("fusion", "serve"):
            # lstm/ssd honor BENCH_ITERS too, so they need the same
            # short-timing-record gate as resnet — keyed on the CANONICAL
            # full-run counts, not the env-derived value (ADVICE r5 low);
            # bert/bert512 ladders use fixed iter counts no env can shorten
            leg_min_iters = FULL_RUN_ITERS.get(name)
            cached = _fresh_stored(
                extra_metrics[name], skip_fresh,
                require={"backbone": ssd_backbone} if name == "ssd"
                else None, min_iters=leg_min_iters,
                validate=_bert512_complete if name == "bert512" else None)
            if cached is not None:
                log(f"{name}: carrying fresh record from "
                    f"{cached['measured_at']} (BENCH_SKIP_FRESH)")
                extra_recs[name] = cached
                continue
        try:
            r = fn_extra(smoke)
            log(f"{name} record: " + json.dumps(r))
            persist_lastgood(r)
            extra_recs[name] = r
        except Exception as e:
            log(f"{name} bench failed: {type(e).__name__}: {e}")
            extra_recs[name] = {"metric": extra_metrics[name], "value": 0.0,
                                "unit": "", "vs_baseline": None,
                                "error": f"{type(e).__name__}: {e}"[:300]}
    if rec is None and bert_rec is None and scal_rec is None and \
            not any("error" not in r for r in extra_recs.values()):
        raise SystemExit("every requested benchmark failed; see stderr")
    if rec is None:
        rec = bert_rec or scal_rec or next(
            (r for r in extra_recs.values() if "error" not in r), None)
    if bert_rec is not None and rec is not bert_rec:
        rec["bert"] = bert_rec
    if scal_rec is not None and rec is not scal_rec:
        rec["scaling"] = scal_rec
    for name, r in extra_recs.items():
        if rec is not r:
            rec[name] = r
    # no final persist: every successful record was already persisted
    # under its own metric key at measurement time, and re-persisting the
    # combined record here would store the primary key WITH nested
    # sub-records — the store pollution the per-key design exists to
    # avoid (load_lastgood grafts the freshest subs back at read time)
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# outer: supervisor — no jax import, hard timeouts, retry, partial JSON
# ---------------------------------------------------------------------------
def _acquire_chip_lock():
    """Cooperative single-chip lock (flock on .chip_lock, self-releasing
    on process death): the round-end driver bench and a mid-stage
    tpu_watch must not hit the chip concurrently — two jax processes
    wedge each other in make_c_api_client and BOTH lose.  The watcher
    holds the lock around each stage and sets TPUMX_CHIP_LOCK_HELD=1 for
    its children (this outer runs AS such a child: skip re-acquiring the
    lock the parent already holds).  Bounded wait: a stage tops out at
    90 min but the watcher yields between stages, so waiting a while
    usually wins; after TPUMX_CHIP_LOCK_WAIT (default 1800 s) proceed
    anyway rather than lose the round to patience.  Returns the open
    lock file (hold until exit) or None."""
    if os.environ.get("TPUMX_CHIP_LOCK_HELD") == "1":
        return None
    import fcntl
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".chip_lock")
    f = open(path, "w")
    deadline = time.time() + float(
        os.environ.get("TPUMX_CHIP_LOCK_WAIT", "1800"))
    logged = False
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except OSError:
            remaining = deadline - time.time()
            if remaining <= 0:
                log("chip lock still held at wait deadline; proceeding "
                    "WITHOUT the lock (accepting contention risk)")
                f.close()
                return None  # honest: exclusivity does NOT hold
            if not logged:
                log("chip lock held (a watcher stage is on the chip); "
                    "waiting for it to finish...")
                logged = True
            time.sleep(min(10.0, max(0.5, remaining)))


def _run_attempt(timeout, probe_timeout):
    """Run one --inner child.  The child's stderr is teed through so the
    stage log stays visible, and watched for the 'backend up' marker: a
    wedged tunnel (jax.devices() hanging in a C call — observed for hours
    in round 3) is killed after probe_timeout instead of burning the full
    budget.  Returns (rc, stdout_lines, err_or_None)."""
    import threading
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--inner"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    backend_up = threading.Event()

    def tee():
        for line in proc.stderr:
            sys.stderr.write(line)
            sys.stderr.flush()
            if "backend up" in line:
                backend_up.set()

    t = threading.Thread(target=tee, daemon=True)
    t.start()
    start = time.monotonic()
    while True:
        rc = proc.poll()
        if rc is not None:
            break
        elapsed = time.monotonic() - start
        if not backend_up.is_set() and elapsed > probe_timeout:
            proc.kill()
            proc.wait()
            return None, [], (f"backend probe did not come up within "
                              f"{probe_timeout:.0f}s (tunnel wedged?)")
        if elapsed > timeout:
            proc.kill()
            proc.wait()
            return None, [], f"timed out after {timeout:.0f}s"
        time.sleep(1.0)
    out = (proc.stdout.read() or "").strip().splitlines()
    return rc, out, None


def outer():
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "2"))
    # all four workloads compile+run in one attempt (~13 min measured on
    # the tunneled chip with a cold cache, 2026-07-31); 2400s keeps a
    # slow-but-alive 4-model sweep from being killed mid-run — and
    # per-metric persistence means even a killed attempt keeps its
    # finished legs
    timeout = float(os.environ.get("BENCH_TIMEOUT", "2400"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
    _chip_lock = _acquire_chip_lock()  # held (or None) until process exit
    last_err = "unknown"
    for attempt in range(1, attempts + 1):
        log(f"attempt {attempt}/{attempts} (timeout {timeout:.0f}s, "
            f"probe {probe_timeout:.0f}s)")
        rc, out, err = _run_attempt(timeout, probe_timeout)
        if err is None:
            json_lines = [ln for ln in out if ln.startswith("{")]
            if rc == 0 and json_lines:
                print(json_lines[-1], flush=True)
                return 0
            err = f"rc={rc}, stdout tail: {out[-3:] if out else '(empty)'}"
        last_err = f"attempt {attempt}: {err}"
        if attempt < attempts:
            log(last_err + "; backing off 15s")
            time.sleep(15)
    # every attempt failed — emit the last in-session good measurement,
    # clearly marked stale, instead of surrendering the round's record to
    # a wedged tunnel (VERDICT r3 ask#8); 0.0 only if none ever existed
    measured_at, lastgood = load_lastgood()
    if lastgood is not None:
        rec = dict(lastgood)
        rec["stale"] = True
        rec["measured_at"] = measured_at
        rec["error"] = last_err
        if "iters" not in rec and \
                not str(rec.get("metric", "")).startswith(
                    "weak_scaling_efficiency"):
            # a record without the r5 self-describing fields predates the
            # r5 byte-diet (one-pass BN default, true-bf16 BERT/LSTM/SSD
            # legs): it measured code paths that no longer exist
            rec["stale_note"] = ("measured before the r5 byte-diet "
                                 "changes — see docs/performance.md "
                                 "'r5 byte-diet changes'")
        log(f"all attempts failed; emitting last good measurement "
            f"from {measured_at} marked stale")
        print(json.dumps(rec), flush=True)
        return 0
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": last_err,
    }), flush=True)
    return 0  # JSON was emitted; don't let the driver see a crash


if __name__ == "__main__":
    if "--inner" in sys.argv:
        inner()
    else:
        sys.exit(outer())
