"""Benchmark: ResNet-50 training throughput, single chip (BASELINE headline).

Runs the full compiled train step (fwd+bwd+SGD update in one XLA program,
bf16 compute / f32 master state, channels-last NHWC layout) and prints ONE
JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N, "mfu": ...}
vs_baseline is against the A100 ballpark in BASELINE.md (~2800 img/s AMP).

Engineering for the tunneled TPU backend (BENCH_r01 failure + VERDICT weak#1):
backend init can hang indefinitely inside a C call, which no in-process
timeout can interrupt.  So the outer process (this file, run with no args)
imports NO jax; it supervises `python bench.py --inner` children with a hard
timeout and retry/backoff, streams the child's stage prints to stderr, and
ALWAYS emits a JSON line — a real number, or a partial record with "error"
set if every attempt died.

Env knobs: BENCH_SMOKE=1 (CPU smoke, small shapes), BENCH_LAYOUT=NCHW
(default NHWC), BENCH_BATCH / BENCH_ITERS overrides, BENCH_ATTEMPTS (default
3), BENCH_TIMEOUT seconds per attempt (default 600).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_BASELINE = 2800.0  # img/s, BASELINE.md ballpark
V5E_PEAK_FLOPS = 197e12  # bf16 peak, TPU v5e chip
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9  # fwd GMACs*2, *3 for fwd+bwd


def log(msg):
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


# ---------------------------------------------------------------------------
# inner: the actual benchmark (may hang on a flaky backend; outer kills us)
# ---------------------------------------------------------------------------
def inner():
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")
    log(f"inner start (smoke={smoke}, layout={layout})")

    import jax
    if smoke:
        jax.config.update("jax_platforms", "cpu")

    log("probing backend (jax.devices)...")
    t0 = time.perf_counter()
    devs = jax.devices()
    log(f"backend up: {devs[0].platform} x{len(devs)} "
        f"in {time.perf_counter() - t0:.1f}s")

    log("staged warmup: tiny jit matmul...")
    import jax.numpy as jnp
    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.bfloat16)
    jax.jit(lambda a: a @ a)(x).block_until_ready()
    log(f"tiny jit ok in {time.perf_counter() - t0:.1f}s")

    import numpy as np
    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.layout import default_layout
    from tpu_mx.parallel import CompiledTrainStep

    if smoke:
        batch, size, warmup, iters = 8, 64, 1, 3
        classes, factory = 100, "resnet18_v1"
    else:
        batch, size, warmup, iters = 256, 224, 3, 30
        classes, factory = 1000, "resnet50_v1"
    batch = int(os.environ.get("BENCH_BATCH", batch))
    iters = int(os.environ.get("BENCH_ITERS", iters))

    log(f"building {factory} ({layout}), batch={batch}, size={size}")
    shape = (batch, size, size, 3) if layout == "NHWC" else (batch, 3, size, size)
    with default_layout(layout):
        net = getattr(vision, factory)(classes=classes)
    net.initialize(init="xavier")
    x = nd.array(np.random.rand(*shape).astype(np.float32))
    _ = net(x)  # finalize deferred shapes
    net.cast("bfloat16")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4, multi_precision=True)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)

    data = nd.cast(nd.array(np.random.rand(*shape).astype(np.float32)),
                   "bfloat16")
    label = nd.array(np.random.randint(0, classes, (batch,)), dtype="float32")

    log("compiling full train step (first call)...")
    t0 = time.perf_counter()

    # Sync via a host fetch of the loss scalar, not wait_to_read: on the
    # tunneled single-chip backend block_until_ready returns before the
    # computation finishes, which silently inflates throughput ~10x.  The
    # loss depends on the full weight-update chain, so fetching it bounds
    # every queued step.  Tunnel latency is also noisy (hundreds-of-ms
    # spikes), so take the best of several repeats of a long-ish run.
    def timed_run(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = step.step(data, label)
        float(np.asarray(loss._data).ravel()[0])
        return time.perf_counter() - t0

    timed_run(1)
    log(f"first step (compile+run) {time.perf_counter() - t0:.1f}s; warmup...")
    for _ in range(warmup):
        timed_run(1)
    log(f"timing {iters} steps x repeats...")
    repeats = 1 if smoke else 3
    best = None
    for r in range(repeats):
        dt = timed_run(iters)
        log(f"  repeat {r}: {dt:.3f}s ({batch * iters / dt:.1f} img/s)")
        best = dt if best is None else min(best, dt)

    img_s = batch * iters / best
    mfu = (img_s * RESNET50_TRAIN_FLOPS_PER_IMG / V5E_PEAK_FLOPS
           if not smoke else None)
    rec = {
        "metric": "resnet50_train_images_per_sec_per_chip"
        if not smoke else "resnet18_smoke_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / A100_BASELINE, 4),
    }
    if mfu is not None:
        rec["mfu"] = round(mfu, 4)
    rec["layout"] = layout
    rec["batch"] = batch
    print(json.dumps(rec), flush=True)


# ---------------------------------------------------------------------------
# outer: supervisor — no jax import, hard timeouts, retry, partial JSON
# ---------------------------------------------------------------------------
def outer():
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "3"))
    timeout = float(os.environ.get("BENCH_TIMEOUT", "600"))
    last_err = "unknown"
    for attempt in range(1, attempts + 1):
        log(f"attempt {attempt}/{attempts} (timeout {timeout:.0f}s)")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner"],
                stdout=subprocess.PIPE, timeout=timeout, text=True)
        except subprocess.TimeoutExpired:
            last_err = f"attempt {attempt} timed out after {timeout:.0f}s"
            log(last_err + "; backing off 15s")
            time.sleep(15)
            continue
        out = (proc.stdout or "").strip().splitlines()
        json_lines = [ln for ln in out if ln.startswith("{")]
        if proc.returncode == 0 and json_lines:
            print(json_lines[-1], flush=True)
            return 0
        last_err = (f"attempt {attempt} rc={proc.returncode}, "
                    f"stdout tail: {out[-3:] if out else '(empty)'}")
        log(last_err + "; backing off 15s")
        time.sleep(15)
    # every attempt failed — still emit parseable JSON for the driver
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": last_err,
    }), flush=True)
    return 0  # JSON was emitted; don't let the driver see a crash


if __name__ == "__main__":
    if "--inner" in sys.argv:
        inner()
    else:
        sys.exit(outer())
