"""Benchmark: ResNet-50 training throughput, single chip (BASELINE headline).

Runs the full compiled train step (fwd+bwd+SGD update in one XLA program,
bf16 compute / f32 master state) and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}
vs_baseline is against the A100 ballpark in BASELINE.md (~2800 img/s AMP).

Env: BENCH_SMOKE=1 shrinks shapes for a CPU smoke run.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    if smoke:
        jax.config.update("jax_platforms", "cpu")

    import tpu_mx as mx
    from tpu_mx import gluon, nd
    from tpu_mx.gluon.model_zoo import vision
    from tpu_mx.parallel import CompiledTrainStep

    if smoke:
        batch, size, warmup, iters = 8, 64, 1, 3
        net = vision.resnet18_v1(classes=100)
    else:
        batch, size, warmup, iters = 128, 224, 3, 30
        net = vision.resnet50_v1(classes=1000)

    net.initialize(init="xavier")
    x = nd.array(np.random.rand(batch, 3, size, size).astype(np.float32))
    _ = net(x)  # finalize deferred shapes
    net.cast("bfloat16")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              wd=1e-4, multi_precision=True)
    step = CompiledTrainStep(net, loss_fn, opt, mesh=None)

    data = nd.cast(
        nd.array(np.random.rand(batch, 3, size, size).astype(np.float32)),
        "bfloat16")
    label = nd.array(np.random.randint(0, 100 if smoke else 1000, (batch,)),
                     dtype="float32")

    # Sync via a host fetch of the loss scalar, not wait_to_read: on the
    # tunneled single-chip backend block_until_ready returns before the
    # computation finishes, which silently inflates throughput ~10x.  The
    # loss depends on the full weight-update chain, so fetching it bounds
    # every queued step.  Tunnel latency is also noisy (hundreds-of-ms
    # spikes), so take the best of several repeats of a long-ish run.
    def timed_run(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            loss = step.step(data, label)
        float(np.asarray(loss._data).ravel()[0])
        return time.perf_counter() - t0

    for _ in range(warmup):
        timed_run(1)
    repeats = 1 if smoke else 3
    dt = min(timed_run(iters) for _ in range(repeats))

    img_s = batch * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip"
        if not smoke else "resnet18_smoke_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / 2800.0, 4),
    }))


if __name__ == "__main__":
    main()
