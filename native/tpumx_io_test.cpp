// Native unit tests for tpumx_io.cpp internals — the C++ test tier
// (SURVEY §4: REF:tests/cpp/{engine,storage,operator} used googletest;
// here plain asserts + a main(), compiled and run by
// tests/test_native_io.py::test_native_cpp_unit_tier, keeping the image's
// toolchain requirements at just g++).
//
// Units covered (the ones Python-level tests can only reach indirectly):
// HashUniform (counter-based determinism + range), ResizeBilinear
// (identity / constant preservation / known 2x upscale), RecordIO scan
// (whole + split + corrupt), and the det label header bounds check
// (uint32 overflow regression).
#include "tpumx_io.cpp"

#include <sys/resource.h>

#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstdlib>

namespace {

int failures = 0;

#define CHECK_TRUE(cond)                                                   \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);      \
      failures++;                                                          \
    }                                                                      \
  } while (0)

void TestHashUniform() {
  // deterministic across calls, uniform-ish in [0, 1)
  for (uint64_t a = 0; a < 4; ++a) {
    float x = HashUniform(7, a, 13, 2);
    float y = HashUniform(7, a, 13, 2);
    CHECK_TRUE(x == y);
    CHECK_TRUE(x >= 0.0f && x < 1.0f);
  }
  // distinct counters give distinct draws (overwhelmingly)
  int distinct = 0;
  for (int i = 0; i < 32; ++i) {
    if (HashUniform(7, i, 0, 0) != HashUniform(7, i + 1, 0, 0)) distinct++;
  }
  CHECK_TRUE(distinct >= 30);
  // crude mean check over many draws
  double s = 0;
  for (int i = 0; i < 4096; ++i) s += HashUniform(3, i, 1, 2);
  CHECK_TRUE(std::fabs(s / 4096 - 0.5) < 0.05);
}

void TestResizeBilinear() {
  // identity
  std::vector<uint8_t> src(4 * 5 * 3);
  for (size_t i = 0; i < src.size(); ++i) src[i] = i % 251;
  std::vector<uint8_t> dst(src.size());
  ResizeBilinear(src.data(), 4, 5, dst.data(), 4, 5);
  CHECK_TRUE(src == dst);
  // constant image stays constant at any size
  std::fill(src.begin(), src.end(), 77);
  std::vector<uint8_t> up(9 * 11 * 3);
  ResizeBilinear(src.data(), 4, 5, up.data(), 9, 11);
  for (uint8_t v : up) CHECK_TRUE(v == 77);
  // 2x upscale of a 2-pixel gradient interpolates between endpoints
  uint8_t grad[2 * 2 * 3] = {0, 0, 0, 100, 100, 100,
                             0, 0, 0, 100, 100, 100};
  uint8_t out[2 * 4 * 3];
  ResizeBilinear(grad, 2, 2, out, 2, 4);
  CHECK_TRUE(out[0] == 0);
  CHECK_TRUE(out[9] >= 95);          // rightmost column ~100
  CHECK_TRUE(out[3] > 0 && out[3] < 100);  // interior interpolated
}

std::string WriteTempRec(const std::vector<std::vector<uint8_t>>& payloads,
                         bool corrupt_magic = false) {
  char name[] = "/tmp/tpumx_io_test_XXXXXX";
  int fd = mkstemp(name);
  FILE* f = fdopen(fd, "wb");
  for (const auto& p : payloads) {
    uint32_t magic = corrupt_magic ? 0xDEADBEEF : kMagic;
    uint32_t lenfield = static_cast<uint32_t>(p.size());  // cflag 0
    fwrite(&magic, 4, 1, f);
    fwrite(&lenfield, 4, 1, f);
    fwrite(p.data(), 1, p.size(), f);
    size_t padded = (p.size() + 3u) & ~3ull;
    uint8_t zero[4] = {0, 0, 0, 0};
    fwrite(zero, 1, padded - p.size(), f);
  }
  fclose(f);
  return name;
}

void TestRecFileScan() {
  std::vector<std::vector<uint8_t>> payloads = {
      std::vector<uint8_t>(10, 1), std::vector<uint8_t>(33, 2),
      std::vector<uint8_t>(7, 3)};
  std::string path = WriteTempRec(payloads);
  RecFile rf;
  std::string err;
  CHECK_TRUE(rf.Open(path.c_str(), &err));
  CHECK_TRUE(rf.records.size() == 3);
  std::vector<uint8_t> buf;
  for (size_t i = 0; i < payloads.size(); ++i) {
    CHECK_TRUE(rf.Read(i, &buf));
    CHECK_TRUE(buf == payloads[i]);
  }
  remove(path.c_str());

  std::string bad = WriteTempRec(payloads, /*corrupt_magic=*/true);
  RecFile rf2;
  CHECK_TRUE(!rf2.Open(bad.c_str(), &err));
  CHECK_TRUE(err.find("magic") != std::string::npos);
  remove(bad.c_str());
}

void TestDetLabelBoundsOverflow() {
  // header flag = 0x40000006 (a true multiple of 5 — 0x40000005 is not!):
  // flag*4 wraps to 24 in uint32, which would PASS a uint32 bounds check
  // against the 64-byte payload and then run boxes.resize(flag) — a ~4 GB
  // allocation (the memcpy uses the same wrapped count, so the hazard is
  // the allocation, not OOB).  Under overcommit that allocation can
  // quietly succeed, so the regression is made OBSERVABLE by capping the
  // address space: with uint32 math the resize throws bad_alloc (and the
  // worker contract would std::terminate); with the size_t fix the
  // record is rejected before any allocation.
  static_assert(0x40000006u % 5 == 0, "flag must pass the %5 guard");
  static_assert(static_cast<uint32_t>(0x40000006u * 4u) == 24u,
                "flag*4 must wrap below the payload size in uint32");
// AddressSanitizer reserves terabytes of virtual address space for its
// shadow, so an RLIMIT_AS cap aborts the RUNTIME, not the hazardous
// allocation.  Under ASAN the regression stays observable through the
// rejection CHECK below (a regressed uint32 bounds check would decode
// the record instead of rejecting it); the allocation-hazard observable
// belongs to the plain build.
#if defined(__SANITIZE_ADDRESS__)
#define TPUMX_ASAN 1
#elif defined(__has_feature)
#if __has_feature(address_sanitizer)
#define TPUMX_ASAN 1
#endif
#endif
#ifndef TPUMX_ASAN
  rlimit old{};
  getrlimit(RLIMIT_AS, &old);
  rlimit capped = old;
  capped.rlim_cur = 1ull << 31;  // 2 GB — far below flag*sizeof(float)
  setrlimit(RLIMIT_AS, &capped);
#endif
  std::vector<uint8_t> rec(24 + 64, 0);
  uint32_t flag = 0x40000006u;
  memcpy(rec.data(), &flag, 4);
  std::string path = WriteTempRec({rec});
  DetPipe p;
  std::string err;
  CHECK_TRUE(p.file.Open(path.c_str(), &err));
  p.batch = 1;
  p.C = 3;
  p.H = 8;
  p.W = 8;
  p.max_objects = 2;
  p.rand_crop = p.rand_mirror = 0;
  for (int i = 0; i < 3; ++i) {
    p.mean[i] = 0;
    p.stdv[i] = 1;
  }
  p.min_cover = 0.3f;
  p.area_lo = 0.3f;
  p.area_hi = 1.0f;
  p.ratio_lo = 0.75f;
  p.ratio_hi = 1.33f;
  p.max_attempts = 1;
  p.seed = 0;
  p.order = {0};
  std::vector<float> img(p.DataElems()), lab(p.LabelElems());
  CHECK_TRUE(!p.DecodeOne(0, img.data(), lab.data()));
#ifndef TPUMX_ASAN
  setrlimit(RLIMIT_AS, &old);
#endif
  remove(path.c_str());
}

}  // namespace

int main() {
  TestHashUniform();
  TestResizeBilinear();
  TestRecFileScan();
  TestDetLabelBoundsOverflow();
  if (failures == 0) {
    printf("tpumx_io_test: ALL PASS\n");
    return 0;
  }
  printf("tpumx_io_test: %d FAILURES\n", failures);
  return 1;
}
