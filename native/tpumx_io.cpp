// tpumx_io — native data pipeline for the TPU-native framework.
//
// TPU-native equivalent of the reference's C++ input stack
// (REF:src/io/iter_image_recordio_2.cc ImageRecordIOParser2 +
//  REF:src/io/iter_prefetcher.h PrefetcherIter +
//  REF:src/io/image_aug_default.cc DefaultImageAugmenter +
//  REF:3rdparty/dmlc-core recordio chunk reader):
// a RecordIO scanner, multithreaded libjpeg decode + augment
// (shorter-side resize, random/center crop, mirror, mean/std normalize,
// NCHW float32 fill), and a bounded in-order prefetch queue, exposed
// through a C ABI consumed via ctypes (no pybind11 in the image).
//
// Determinism: augmentation draws are a counter-based hash of
// (seed, epoch, position) — reproducible for a fixed seed regardless of
// worker scheduling, like the reference's per-batch main-thread draws.
//
// Build: g++ -O3 -shared -fPIC tpumx_io.cpp -o libtpumx_io.so -ljpeg -lpthread

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

// ---------------------------------------------------------------------------
// RecordIO scan: payload extents of every logical record in the file
// ---------------------------------------------------------------------------
struct RecordExtent {
  // a logical record = 1+ physical parts (continuation flags 1/2/3)
  std::vector<std::pair<uint64_t, uint32_t>> parts;  // (offset, length)
  uint64_t total = 0;
};

struct RecFile {
  FILE* fp = nullptr;
  std::vector<RecordExtent> records;
  std::mutex io_mu;

  ~RecFile() {
    if (fp) fclose(fp);
  }

  bool Open(const char* path, std::string* err) {
    fp = fopen(path, "rb");
    if (!fp) {
      *err = std::string("cannot open ") + path;
      return false;
    }
    // sequential scan for record boundaries
    uint64_t pos = 0;
    RecordExtent cur;
    bool in_split = false;
    for (;;) {
      uint32_t head[2];
      if (fread(head, 4, 2, fp) != 2) break;  // EOF
      if (head[0] != kMagic) {
        *err = "corrupt recordio: bad magic";
        return false;
      }
      uint32_t cflag = head[1] >> 29;
      uint32_t len = head[1] & kLenMask;
      uint64_t payload_at = pos + 8;
      uint64_t padded = (len + 3u) & ~3ull;
      if (cflag == 0) {  // whole record
        RecordExtent e;
        e.parts.emplace_back(payload_at, len);
        e.total = len;
        records.push_back(std::move(e));
      } else if (cflag == 1) {  // begin
        cur = RecordExtent();
        cur.parts.emplace_back(payload_at, len);
        cur.total = len;
        in_split = true;
      } else {  // middle / end
        if (!in_split) {
          *err = "corrupt recordio: continuation without begin";
          return false;
        }
        cur.parts.emplace_back(payload_at, len);
        // parts of a split record are rejoined WITH the magic word between
        // them (the writer split exactly at payload-embedded magics —
        // recordio.py MXRecordIO.read does _MAGIC_BYTES.join(parts))
        cur.total += 4 + len;
        if (cflag == 3) {
          records.push_back(std::move(cur));
          in_split = false;
        }
      }
      pos = payload_at + padded;
      if (fseek(fp, static_cast<long>(pos), SEEK_SET) != 0) break;
    }
    return true;
  }

  bool Read(size_t i, std::vector<uint8_t>* out) {
    const RecordExtent& e = records[i];
    out->resize(e.total);
    uint8_t* dst = out->data();
    std::lock_guard<std::mutex> lk(io_mu);
    bool first = true;
    for (const auto& p : e.parts) {
      if (!first) {
        memcpy(dst, &kMagic, 4);  // re-insert the split delimiter
        dst += 4;
      }
      first = false;
      if (fseek(fp, static_cast<long>(p.first), SEEK_SET) != 0) return false;
      if (fread(dst, 1, p.second, fp) != p.second) return false;
      dst += p.second;
    }
    return true;
  }
};

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg, RGB output)
// ---------------------------------------------------------------------------
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

bool DecodeJpeg(const uint8_t* buf, size_t len, std::vector<uint8_t>* rgb,
                int* h, int* w, int min_short_side,
                int* orig_h = nullptr, int* orig_w = nullptr) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  if (orig_h) *orig_h = cinfo.image_height;
  if (orig_w) *orig_w = cinfo.image_width;
  // DCT-domain downscale: decode at 1/2^k when the target short side
  // allows — decode cost drops ~4x per halving (the reference gets this
  // from OpenCV's IMREAD_REDUCED path; ImageRecordIOParser2 decodes full)
  if (min_short_side > 0) {
    unsigned src_short = cinfo.image_height < cinfo.image_width
                             ? cinfo.image_height
                             : cinfo.image_width;
    unsigned denom = 1;
    while (denom < 8 &&
           src_short / (denom * 2) >= static_cast<unsigned>(min_short_side)) {
      denom *= 2;
    }
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  rgb->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb->data() +
        static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ---------------------------------------------------------------------------
// bilinear resize, uint8 RGB HWC
// ---------------------------------------------------------------------------
void ResizeBilinear(const uint8_t* src, int sh, int sw, uint8_t* dst, int dh,
                    int dw) {
  // pixel-center alignment (matches OpenCV INTER_LINEAR convention);
  // x-axis taps/weights precomputed once per call, 3-channel inner loop
  // flat enough for the autovectorizer
  if (sh == dh && sw == dw) {
    memcpy(dst, src, static_cast<size_t>(sh) * sw * 3);
    return;
  }
  const float sy = static_cast<float>(sh) / dh;
  const float sx = static_cast<float>(sw) / dw;
  std::vector<int> xt0(dw), xt1(dw);
  std::vector<float> xw(dw);
  for (int x = 0; x < dw; ++x) {
    float fx = (x + 0.5f) * sx - 0.5f;
    int x0 = fx < 0 ? 0 : static_cast<int>(fx);
    xt0[x] = x0 * 3;
    xt1[x] = (x0 + 1 < sw ? x0 + 1 : sw - 1) * 3;
    float wx = fx - x0;
    xw[x] = wx < 0 ? 0 : wx;
  }
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = fy < 0 ? 0 : static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    const uint8_t* r0 = src + static_cast<size_t>(y0) * sw * 3;
    const uint8_t* r1 = src + static_cast<size_t>(y1) * sw * 3;
    uint8_t* drow = dst + static_cast<size_t>(y) * dw * 3;
    const float w1my = 1 - wy;
    for (int x = 0; x < dw; ++x) {
      const int a = xt0[x], b = xt1[x];
      const float wx = xw[x], w1mx = 1 - wx;
      const float w00 = w1my * w1mx, w01 = w1my * wx;
      const float w10 = wy * w1mx, w11 = wy * wx;
      for (int c = 0; c < 3; ++c) {
        float v = r0[a + c] * w00 + r0[b + c] * w01 +
                  r1[a + c] * w10 + r1[b + c] * w11;
        drow[x * 3 + c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// counter-based hash → uniform floats (determinism independent of threads)
inline uint64_t Mix(uint64_t z) {
  z += 0x9e3779b97f4a7c15ull;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

inline float HashUniform(uint64_t seed, uint64_t a, uint64_t b, uint64_t c) {
  uint64_t m = Mix(seed ^ Mix(a ^ Mix(b ^ Mix(c))));
  return static_cast<float>(m >> 11) * (1.0f / 9007199254740992.0f);
}

// ---------------------------------------------------------------------------
// pipeline
// ---------------------------------------------------------------------------
// Shared threaded batch machinery: an epoch is a ticket sequence over
// (shuffled) record positions; workers decode into prefetch ring slots,
// the consumer (Python thread) drains completed batches in order.  The
// classification Pipe and the detection DetPipe differ only in per-item
// decode+augment and in output element counts — virtual-dispatch cost is
// noise next to a JPEG decode.
struct PipeBase {
  RecFile file;
  int batch;
  int nthreads, prefetch;
  int shuffle;
  uint64_t seed;
  std::string error;

  std::vector<uint32_t> order;
  uint64_t epoch = 0;

  // work state (one epoch)
  std::atomic<uint64_t> next_record{0};  // global ticket over epoch positions
  uint64_t total_batches = 0;

  struct BatchBuf {
    std::vector<uint8_t> data;  // raw bytes: batch * DataElems * ElemSize
    std::vector<float> label;
    std::atomic<int> done{0};
    uint64_t seq = ~0ull;
  };
  std::vector<BatchBuf> bufs;  // prefetch slots; slot = seq % bufs.size()
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  uint64_t consumed = 0;  // batches handed to python
  std::atomic<bool> stop{false};
  std::atomic<bool> failed{false};
  std::vector<std::thread> workers;

  virtual ~PipeBase() = default;
  virtual bool DecodeOne(uint64_t pos, void* img_out, float* label_out) = 0;
  virtual size_t DataElems() const = 0;   // per-item data elements
  virtual size_t LabelElems() const = 0;  // per-item label floats
  // bytes per data element: 4 (float32, default) or 1 (uint8 feed —
  // normalization then happens on device, and host+interconnect move 4x
  // fewer bytes)
  virtual size_t ElemSize() const { return 4; }

  void AllocBufs() {
    bufs = std::vector<BatchBuf>(prefetch);
    for (auto& b : bufs) {
      b.data.resize(static_cast<size_t>(batch) * DataElems() * ElemSize());
      b.label.resize(static_cast<size_t>(batch) * LabelElems());
    }
  }

  void StartEpoch() {
    StopWorkers();
    failed = false;  // a decode failure poisons one epoch, not the pipe
    error.clear();
    uint64_t n = order.size();
    total_batches = (n + batch - 1) / batch;
    next_record = 0;
    consumed = 0;
    for (auto& b : bufs) {
      b.done = 0;
      b.seq = ~0ull;
    }
    if (shuffle) {
      std::mt19937_64 rng(seed + 0x517cc1b7 * (epoch + 1));
      for (size_t i = order.size(); i > 1; --i) {
        std::swap(order[i - 1], order[rng() % i]);
      }
    }
    stop = false;
    for (int t = 0; t < nthreads; ++t) {
      workers.emplace_back([this] { WorkerLoop(); });
    }
  }

  void StopWorkers() {
    stop = true;
    cv_free.notify_all();
    for (auto& t : workers) t.join();
    workers.clear();
  }

  void WorkerLoop() {
    const uint64_t nrec = total_batches * batch;  // padded epoch length
    for (;;) {
      uint64_t pos = next_record.fetch_add(1);
      if (pos >= nrec || stop || failed) return;
      uint64_t bseq = pos / batch;
      size_t slot = bseq % bufs.size();
      BatchBuf& bb = bufs[slot];
      {
        // wait until this slot is free (its previous batch consumed)
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          return stop.load() || failed.load() || bseq < consumed + bufs.size();
        });
        if (stop || failed) return;
        if (bb.seq != bseq) {
          bb.seq = bseq;
          bb.done = 0;
        }
      }
      int in_batch = static_cast<int>(pos % batch);
      void* img = bb.data.data() +
                  static_cast<size_t>(in_batch) * DataElems() * ElemSize();
      float* lab = bb.label.data() +
                   static_cast<size_t>(in_batch) * LabelElems();
      if (!DecodeOne(pos, img, lab)) {
        std::lock_guard<std::mutex> lk(mu);
        failed = true;
        error = "record decode failed at epoch position " +
                std::to_string(pos);
        cv_ready.notify_all();
        cv_free.notify_all();
        return;
      }
      if (bb.done.fetch_add(1) + 1 == batch) {
        std::lock_guard<std::mutex> lk(mu);
        cv_ready.notify_all();
      }
    }
  }

  // returns records delivered (batch), 0 at epoch end, -1 on failure
  int Next(void* data_out, float* label_out) {
    if (consumed >= total_batches) return 0;
    uint64_t bseq = consumed;
    size_t slot = bseq % bufs.size();
    BatchBuf& bb = bufs[slot];
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_ready.wait(lk, [&] {
        return failed.load() || (bb.seq == bseq && bb.done.load() == batch);
      });
      if (failed) return -1;
    }
    memcpy(data_out, bb.data.data(), bb.data.size());
    memcpy(label_out, bb.label.data(), bb.label.size() * sizeof(float));
    {
      std::lock_guard<std::mutex> lk(mu);
      consumed++;
      cv_free.notify_all();
    }
    return batch;
  }
};

// ---------------------------------------------------------------------------
// classification pipeline (REF:src/io/iter_image_recordio_2.cc)
// ---------------------------------------------------------------------------
// ---------------------------------------------------------------------------
// shared crop/mirror/emit for both pipes: {f32,u8} x {CHW,HWC} in one
// pass.  `src` points at the first row of the source image (already
// resized), `stride_w` is its full row width in pixels, `x0` the crop
// column offset (0 when the source is exactly the crop).  u8 skips
// normalization entirely — it happens on device (DevicePrefetchIter).
// ---------------------------------------------------------------------------
static void EmitImage(const uint8_t* src, int stride_w, int x0, int C,
                      int H, int W, bool mirror, int out_u8, int out_nhwc,
                      const float* mean, const float* stdv,
                      void* img_out_v) {
  if (out_u8 && out_nhwc) {
    uint8_t* out = static_cast<uint8_t*>(img_out_v);
    for (int yy = 0; yy < H; ++yy) {
      const uint8_t* row =
          src + (static_cast<size_t>(yy) * stride_w + x0) * 3;
      uint8_t* drow = out + static_cast<size_t>(yy) * W * 3;
      if (mirror) {
        for (int xx = 0; xx < W; ++xx) {
          const uint8_t* px = row + (W - 1 - xx) * 3;
          drow[xx * 3] = px[0];
          drow[xx * 3 + 1] = px[1];
          drow[xx * 3 + 2] = px[2];
        }
      } else {
        memcpy(drow, row, static_cast<size_t>(W) * 3);
      }
    }
  } else if (out_u8) {
    uint8_t* out = static_cast<uint8_t*>(img_out_v);
    for (int c = 0; c < C && c < 3; ++c) {
      uint8_t* dst = out + static_cast<size_t>(c) * H * W;
      for (int yy = 0; yy < H; ++yy) {
        const uint8_t* row =
            src + (static_cast<size_t>(yy) * stride_w + x0) * 3 + c;
        uint8_t* drow = dst + static_cast<size_t>(yy) * W;
        if (mirror) {
          for (int xx = 0; xx < W; ++xx) drow[xx] = row[(W - 1 - xx) * 3];
        } else {
          for (int xx = 0; xx < W; ++xx) drow[xx] = row[xx * 3];
        }
      }
    }
  } else if (out_nhwc) {
    float* out = static_cast<float*>(img_out_v);
    float inv[3], mu[3];
    for (int c = 0; c < 3; ++c) {
      mu[c] = mean[c];
      inv[c] = 1.0f / stdv[c];
    }
    for (int yy = 0; yy < H; ++yy) {
      const uint8_t* row =
          src + (static_cast<size_t>(yy) * stride_w + x0) * 3;
      float* drow = out + static_cast<size_t>(yy) * W * 3;
      for (int xx = 0; xx < W; ++xx) {
        const uint8_t* px = row + (mirror ? (W - 1 - xx) : xx) * 3;
        drow[xx * 3] = (px[0] - mu[0]) * inv[0];
        drow[xx * 3 + 1] = (px[1] - mu[1]) * inv[1];
        drow[xx * 3 + 2] = (px[2] - mu[2]) * inv[2];
      }
    }
  } else {
    float* img_out = static_cast<float*>(img_out_v);
    for (int c = 0; c < C && c < 3; ++c) {
      float mu_ = mean[c], inv = 1.0f / stdv[c];
      float* dst = img_out + static_cast<size_t>(c) * H * W;
      for (int yy = 0; yy < H; ++yy) {
        const uint8_t* row =
            src + (static_cast<size_t>(yy) * stride_w + x0) * 3 + c;
        float* drow = dst + static_cast<size_t>(yy) * W;
        if (mirror) {
          for (int xx = 0; xx < W; ++xx) {
            drow[xx] = (row[(W - 1 - xx) * 3] - mu_) * inv;
          }
        } else {
          for (int xx = 0; xx < W; ++xx) {
            drow[xx] = (row[xx * 3] - mu_) * inv;
          }
        }
      }
    }
  }
}


struct Pipe : PipeBase {
  int C, H, W, resize, rand_crop, rand_mirror;
  float mean[3], stdv[3];
  int label_width;
  // TPU-feed variants: uint8 output (normalize moves on-device; 4x fewer
  // host/interconnect bytes) and NHWC layout (the lane-friendly layout
  // the TPU conv path wants — skips the host-side HWC->CHW transpose)
  int out_u8 = 0, out_nhwc = 0;

  size_t DataElems() const override {
    return static_cast<size_t>(C) * H * W;
  }
  size_t LabelElems() const override { return label_width; }
  size_t ElemSize() const override { return out_u8 ? 1 : 4; }

  bool DecodeOne(uint64_t pos, void* img_out_v, float* label_out) override {
    uint32_t rec_idx = order[pos % order.size()];
    // per-thread scratch: no per-record heap churn in the hot loop
    static thread_local std::vector<uint8_t> raw;
    if (!file.Read(rec_idx, &raw) || raw.size() < 24) return false;
    // IRHeader: uint32 flag; float label; uint64 id, id2 (recordio.py 'IfQQ')
    uint32_t flag;
    float label1;
    memcpy(&flag, raw.data(), 4);
    memcpy(&label1, raw.data() + 4, 4);
    const uint8_t* payload = raw.data() + 24;
    size_t payload_len = raw.size() - 24;
    std::vector<float> labels;
    if (flag > 0) {
      size_t nl = flag;
      if (payload_len < nl * 4) return false;
      labels.resize(nl);
      memcpy(labels.data(), payload, nl * 4);
      payload += nl * 4;
      payload_len -= nl * 4;
    } else {
      labels.assign(1, label1);
    }
    for (int i = 0; i < label_width; ++i) {
      label_out[i] = i < static_cast<int>(labels.size()) ? labels[i] : 0.0f;
    }

    static thread_local std::vector<uint8_t> rgb;
    int ih = 0, iw = 0;
    // DCT-scale only when a shorter-side resize follows (geometry is then
    // normalized); without resize the crop must come from the full-res
    // image to match reference semantics
    int min_short = resize > 0 ? resize : 0;
    if (!DecodeJpeg(payload, payload_len, &rgb, &ih, &iw, min_short)) {
      return false;
    }

    // shorter-side resize, then ensure >= crop size (image_aug_default.cc)
    static thread_local std::vector<uint8_t> tmp;
    if (resize > 0) {
      int short_side = ih < iw ? ih : iw;
      float scale = static_cast<float>(resize) / short_side;
      int nh = static_cast<int>(ih * scale + 0.5f);
      int nw = static_cast<int>(iw * scale + 0.5f);
      if (nh < H) nh = H;
      if (nw < W) nw = W;
      tmp.resize(static_cast<size_t>(nh) * nw * 3);
      ResizeBilinear(rgb.data(), ih, iw, tmp.data(), nh, nw);
      rgb.swap(tmp);
      ih = nh;
      iw = nw;
    }
    if (ih < H || iw < W) {
      int nh = ih < H ? H : ih, nw = iw < W ? W : iw;
      tmp.resize(static_cast<size_t>(nh) * nw * 3);
      ResizeBilinear(rgb.data(), ih, iw, tmp.data(), nh, nw);
      rgb.swap(tmp);
      ih = nh;
      iw = nw;
    }

    int y, x;
    bool mirror = false;
    if (rand_crop) {
      y = static_cast<int>(HashUniform(seed, epoch, pos, 0) * (ih - H + 1));
      x = static_cast<int>(HashUniform(seed, epoch, pos, 1) * (iw - W + 1));
    } else {
      y = (ih - H) / 2;
      x = (iw - W) / 2;
    }
    if (rand_mirror) mirror = HashUniform(seed, epoch, pos, 2) < 0.5f;

    EmitImage(rgb.data() + static_cast<size_t>(y) * iw * 3, iw, x, C, H,
              W, mirror, out_u8, out_nhwc, mean, stdv, img_out_v);
    return true;
  }

};

// ---------------------------------------------------------------------------
// detection pipeline (REF:src/io/iter_image_det_recordio.cc +
// image_det_aug_default.cc).  Per-record label is a flat
// [cls,x1,y1,x2,y2]*m float block (normalized corners, the ImageDetIter
// contract); the output label is a fixed (max_objects, 5) block padded
// with -1 — the static-shape input MultiBoxTarget wants on TPU.
// Augments (same order as image/detection.py CreateDetAugmenter):
// IoU-constrained random crop → horizontal flip (boxes transformed) →
// force-resize to (W, H) → mean/std normalize → CHW.  All randomness is
// counter-based (HashUniform) so epochs replay deterministically
// regardless of thread schedule.
// ---------------------------------------------------------------------------
struct DetPipe : PipeBase {
  int C, H, W, max_objects;
  int rand_crop, rand_mirror;
  float mean[3], stdv[3];
  float min_cover, area_lo, area_hi, ratio_lo, ratio_hi;
  int max_attempts;

  size_t DataElems() const override {
    return static_cast<size_t>(C) * H * W;
  }
  size_t LabelElems() const override {
    return static_cast<size_t>(max_objects) * 5;
  }

  // TPU-feed variants, same contract as the classification Pipe
  int out_u8 = 0, out_nhwc = 0;
  size_t ElemSize() const override { return out_u8 ? 1 : 4; }

  bool DecodeOne(uint64_t pos, void* img_out_v, float* label_out) override {
    uint32_t rec_idx = order[pos % order.size()];
    static thread_local std::vector<uint8_t> raw;
    if (!file.Read(rec_idx, &raw) || raw.size() < 24) return false;
    uint32_t flag;
    memcpy(&flag, raw.data(), 4);
    const uint8_t* payload = raw.data() + 24;
    size_t payload_len = raw.size() - 24;
    // size_t math: a corrupt header's flag*4 must not wrap in uint32 and
    // sneak a huge label block past the bounds check
    size_t label_bytes = static_cast<size_t>(flag) * 4;
    if (flag == 0 || flag % 5 || payload_len < label_bytes) {
      return false;  // det records must carry [cls,x1,y1,x2,y2]*m labels
    }
    int m = static_cast<int>(flag / 5);
    static thread_local std::vector<float> boxes;  // (m, 5)
    boxes.resize(flag);
    memcpy(boxes.data(), payload, label_bytes);
    payload += label_bytes;
    payload_len -= label_bytes;

    static thread_local std::vector<uint8_t> rgb;
    int ih = 0, iw = 0;
    if (!DecodeJpeg(payload, payload_len, &rgb, &ih, &iw, 0)) return false;

    // --- IoU-constrained random crop in normalized coords --------------
    float cx0 = 0.0f, cy0 = 0.0f, cw = 1.0f, ch = 1.0f;
    bool cropped = false;
    if (rand_crop) {
      for (int a = 0; a < max_attempts && !cropped; ++a) {
        uint64_t c0 = 16 + static_cast<uint64_t>(a) * 4;
        float scale = area_lo +
            HashUniform(seed, epoch, pos, c0) * (area_hi - area_lo);
        float ratio = ratio_lo +
            HashUniform(seed, epoch, pos, c0 + 1) * (ratio_hi - ratio_lo);
        float tw = std::sqrt(scale * ratio);
        float th = std::sqrt(scale / ratio);
        if (tw > 1.0f) tw = 1.0f;
        if (th > 1.0f) th = 1.0f;
        float tx0 = HashUniform(seed, epoch, pos, c0 + 2) * (1.0f - tw);
        float ty0 = HashUniform(seed, epoch, pos, c0 + 3) * (1.0f - th);
        // any valid box covered enough?
        for (int i = 0; i < m; ++i) {
          const float* b = boxes.data() + i * 5;
          if (b[0] < 0) continue;
          float ix1 = b[1] > tx0 ? b[1] : tx0;
          float iy1 = b[2] > ty0 ? b[2] : ty0;
          float ix2 = b[3] < tx0 + tw ? b[3] : tx0 + tw;
          float iy2 = b[4] < ty0 + th ? b[4] : ty0 + th;
          float inter = (ix2 > ix1 ? ix2 - ix1 : 0.0f) *
                        (iy2 > iy1 ? iy2 - iy1 : 0.0f);
          float area = (b[3] - b[1]) * (b[4] - b[2]);
          if (area > 0 && inter / area >= min_cover) {
            cx0 = tx0;
            cy0 = ty0;
            cw = tw;
            ch = th;
            cropped = true;
            break;
          }
        }
      }
    }

    bool mirror =
        rand_mirror && HashUniform(seed, epoch, pos, 3) < 0.5f;

    // --- labels: remap surviving boxes, pad with -1 ---------------------
    for (int i = 0; i < max_objects * 5; ++i) label_out[i] = -1.0f;
    int out_rows = 0;
    for (int i = 0; i < m && out_rows < max_objects; ++i) {
      const float* b = boxes.data() + i * 5;
      if (b[0] < 0) continue;
      float x1 = b[1], y1 = b[2], x2 = b[3], y2 = b[4];
      if (cropped) {
        float ix1 = x1 > cx0 ? x1 : cx0;
        float iy1 = y1 > cy0 ? y1 : cy0;
        float ix2 = x2 < cx0 + cw ? x2 : cx0 + cw;
        float iy2 = y2 < cy0 + ch ? y2 : cy0 + ch;
        float inter = (ix2 > ix1 ? ix2 - ix1 : 0.0f) *
                      (iy2 > iy1 ? iy2 - iy1 : 0.0f);
        float area = (x2 - x1) * (y2 - y1);
        if (!(area > 0) || inter / area < min_cover) continue;  // dropped
        auto clip01 = [](float v) {
          return v < 0.0f ? 0.0f : (v > 1.0f ? 1.0f : v);
        };
        x1 = clip01((x1 - cx0) / cw);
        y1 = clip01((y1 - cy0) / ch);
        x2 = clip01((x2 - cx0) / cw);
        y2 = clip01((y2 - cy0) / ch);
      }
      if (mirror) {
        float ox1 = x1;
        x1 = 1.0f - x2;
        x2 = 1.0f - ox1;
      }
      float* dst = label_out + out_rows * 5;
      dst[0] = b[0];
      dst[1] = x1;
      dst[2] = y1;
      dst[3] = x2;
      dst[4] = y2;
      out_rows++;
    }

    // --- pixels: crop rect → contiguous → resize (W, H) -----------------
    int px0 = static_cast<int>(cx0 * iw);
    int py0 = static_cast<int>(cy0 * ih);
    int px1 = static_cast<int>((cx0 + cw) * iw);
    int py1 = static_cast<int>((cy0 + ch) * ih);
    if (px1 > iw) px1 = iw;
    if (py1 > ih) py1 = ih;
    if (px1 - px0 < 1) px1 = px0 + 1;
    if (py1 - py0 < 1) py1 = py0 + 1;
    int sw = px1 - px0, sh = py1 - py0;
    static thread_local std::vector<uint8_t> crop_buf, resized;
    const uint8_t* src = rgb.data();
    if (cropped) {
      crop_buf.resize(static_cast<size_t>(sh) * sw * 3);
      for (int y = 0; y < sh; ++y) {
        memcpy(crop_buf.data() + static_cast<size_t>(y) * sw * 3,
               rgb.data() + ((static_cast<size_t>(py0 + y) * iw) + px0) * 3,
               static_cast<size_t>(sw) * 3);
      }
      src = crop_buf.data();
    } else {
      sh = ih;
      sw = iw;
    }
    resized.resize(static_cast<size_t>(H) * W * 3);
    ResizeBilinear(src, sh, sw, resized.data(), H, W);

    // resized IS the exact H*W*3 crop: stride W, x0 0
    EmitImage(resized.data(), W, 0, C, H, W, mirror, out_u8, out_nhwc,
              mean, stdv, img_out_v);
    return true;
  }
};

}  // namespace

namespace {

// ---------------------------------------------------------------------------
// im2rec: parallel dataset packer (REF:tools/im2rec.cc — the reference's
// C++ packer; same .lst in, same .rec/.idx out as tools/im2rec.py, so the
// two are interchangeable).  Workers read+optionally-recode images; the
// caller's thread writes records IN .lst ORDER and emits the .idx lines.
// ---------------------------------------------------------------------------
struct PackJob {
  size_t seq = 0;
  uint64_t id = 0;
  std::vector<float> labels;
  std::string path;
};

struct PackResult {
  std::vector<uint8_t> payload;  // IRHeader [+labels] + image bytes
  bool ok = false;
  std::string err;
};

bool EncodeJpeg(const uint8_t* rgb, int h, int w, int quality,
                std::vector<uint8_t>* out) {
  jpeg_compress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  uint8_t* mem = nullptr;
  unsigned long mem_len = 0;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_compress(&cinfo);
    if (mem) free(mem);
    return false;
  }
  jpeg_create_compress(&cinfo);
  jpeg_mem_dest(&cinfo, &mem, &mem_len);
  cinfo.image_width = w;
  cinfo.image_height = h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  while (cinfo.next_scanline < cinfo.image_height) {
    const uint8_t* row = rgb + static_cast<size_t>(cinfo.next_scanline) * w * 3;
    jpeg_write_scanlines(&cinfo, const_cast<uint8_t**>(&row), 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  out->assign(mem, mem + mem_len);
  free(mem);
  return true;
}

void BuildPayload(const PackJob& job, std::vector<uint8_t> img_bytes,
                  PackResult* res) {
  // IRHeader (REF dmlc image_recordio.h): uint32 flag, float label,
  // uint64 id, uint64 id2; flag = n_labels when > 1 (labels follow header)
  uint32_t flag = job.labels.size() > 1
                      ? static_cast<uint32_t>(job.labels.size()) : 0u;
  float label0 = job.labels.size() == 1 ? job.labels[0] : 0.0f;
  uint64_t id2 = 0;
  res->payload.reserve(24 + job.labels.size() * 4 + img_bytes.size());
  auto put = [&](const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    res->payload.insert(res->payload.end(), b, b + n);
  };
  put(&flag, 4);
  put(&label0, 4);
  put(&job.id, 8);
  put(&id2, 8);
  if (flag > 0) put(job.labels.data(), job.labels.size() * 4);
  put(img_bytes.data(), img_bytes.size());
  res->ok = true;
}

void PackOne(const std::string& root, int resize, int quality, int upscale,
             const PackJob& job, PackResult* res) {
  std::string full = root.empty() ? job.path : root + "/" + job.path;
  FILE* f = fopen(full.c_str(), "rb");
  if (!f) {
    res->err = "cannot open " + full;
    return;
  }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> bytes(n > 0 ? n : 0);
  if (n > 0 && fread(bytes.data(), 1, n, f) != static_cast<size_t>(n)) {
    fclose(f);
    res->err = "short read " + full;
    return;
  }
  fclose(f);
  // JPEG only (FFD8 magic): the Python path re-encodes png/bmp via
  // OpenCV; this packer has libjpeg alone, and storing undecodable
  // bytes would poison the .rec for the native reader later
  if (bytes.size() < 2 || bytes[0] != 0xFF || bytes[1] != 0xD8) {
    res->err = "not a JPEG (use the Python packer for png/bmp): " + full;
    return;
  }
  if (resize <= 0) {  // store original bytes untouched
    BuildPayload(job, std::move(bytes), res);
    return;
  }
  std::vector<uint8_t> rgb;
  int h = 0, w = 0, oh = 0, ow = 0;
  if (!DecodeJpeg(bytes.data(), bytes.size(), &rgb, &h, &w, resize,
                  &oh, &ow)) {
    res->err = "jpeg decode failed: " + full;
    return;
  }
  // downscale-only decision on the ORIGINAL dimensions (DecodeJpeg may
  // have DCT-downscaled the working copy already)
  int short_side = oh < ow ? oh : ow;
  if (short_side <= resize && !upscale) {
    // Python pack() semantics: only downscale unless --upscale
    BuildPayload(job, std::move(bytes), res);
    return;
  }
  int dh = h, dw = w;
  if (h < w) {
    dh = resize;
    dw = static_cast<int>(static_cast<int64_t>(w) * resize / h);
  } else {
    dw = resize;
    dh = static_cast<int>(static_cast<int64_t>(h) * resize / w);
  }
  std::vector<uint8_t> resized(static_cast<size_t>(dh) * dw * 3);
  ResizeBilinear(rgb.data(), h, w, resized.data(), dh, dw);
  std::vector<uint8_t> jpg;
  if (!EncodeJpeg(resized.data(), dh, dw, quality > 0 ? quality : 95, &jpg)) {
    res->err = "jpeg encode failed: " + full;
    return;
  }
  BuildPayload(job, std::move(jpg), res);
}

}  // namespace

extern "C" {

// Pack a .lst (idx \t label... \t relpath, tab-separated) into
// out_prefix.rec + out_prefix.idx.  resize: shorter-side target (0 = store
// original bytes; >0 downscales only, unless upscale != 0 — the Python
// pack() semantics), quality: jpeg quality for re-encode, nthreads: worker
// count.  JPEG inputs only.  Unreadable/oversized records are SKIPPED with
// a note on stderr (matching the Python packer), and results stream to
// disk in .lst order through a bounded window — O(window) memory, not
// O(dataset).  Returns records written, or -1 with err_buf filled.
long tmx_im2rec(const char* lst_path, const char* root,
                const char* out_prefix, int resize, int quality,
                int nthreads, int upscale, char* err_buf, int err_len) {
  auto fail = [&](const std::string& msg) -> long {
    snprintf(err_buf, err_len, "%s", msg.c_str());
    return -1;
  };
  FILE* lst = fopen(lst_path, "r");
  if (!lst) return fail(std::string("cannot open ") + lst_path);
  std::vector<PackJob> jobs;
  char line[65536];
  while (fgets(line, sizeof(line), lst)) {
    std::vector<std::string> fields;
    char* save = nullptr;
    for (char* tok = strtok_r(line, "\t\r\n", &save); tok;
         tok = strtok_r(nullptr, "\t\r\n", &save)) {
      fields.emplace_back(tok);
    }
    if (fields.size() < 3) continue;  // idx, >=1 label, path
    PackJob j;
    j.seq = jobs.size();
    j.id = strtoull(fields[0].c_str(), nullptr, 10);
    for (size_t i = 1; i + 1 < fields.size(); ++i) {
      j.labels.push_back(strtof(fields[i].c_str(), nullptr));
    }
    j.path = fields.back();
    jobs.push_back(std::move(j));
  }
  fclose(lst);
  if (jobs.empty()) return fail("empty .lst");

  // open outputs BEFORE spawning workers: an early return with joinable
  // threads alive would std::terminate the process
  std::string rec_path = std::string(out_prefix) + ".rec";
  std::string idx_path = std::string(out_prefix) + ".idx";
  FILE* rec = fopen(rec_path.c_str(), "wb");
  if (!rec) return fail("cannot write " + rec_path);
  FILE* idx = fopen(idx_path.c_str(), "w");
  if (!idx) {
    fclose(rec);
    return fail("cannot write " + idx_path);
  }

  const size_t window = 256;  // max in-flight encoded payloads
  std::vector<PackResult> results(jobs.size());
  std::vector<uint8_t> done(jobs.size(), 0);
  std::mutex mu;
  std::condition_variable cv_done, cv_room;
  size_t write_pos = 0;
  std::atomic<bool> abort_flag{false};
  std::atomic<size_t> next{0};
  int nw = nthreads > 0 ? nthreads : 4;
  std::vector<std::thread> workers;
  std::string root_s = root ? root : "";
  for (int t = 0; t < nw; ++t) {
    workers.emplace_back([&] {
      for (;;) {
        size_t i = next.fetch_add(1);
        if (i >= jobs.size()) return;
        {
          // bound memory: don't run ahead of the writer by > window
          std::unique_lock<std::mutex> lk(mu);
          cv_room.wait(lk, [&] {
            return abort_flag.load() || i < write_pos + window;
          });
        }
        if (abort_flag.load()) {  // writer died: stop burning CPU
          std::lock_guard<std::mutex> lk(mu);
          done[i] = 1;
          cv_done.notify_all();
          continue;
        }
        PackOne(root_s, resize, quality, upscale, jobs[i], &results[i]);
        {
          std::lock_guard<std::mutex> lk(mu);
          done[i] = 1;
        }
        cv_done.notify_all();
      }
    });
  }

  uint64_t off = 0;
  long written = 0;
  std::string io_err;
  for (size_t i = 0; i < jobs.size(); ++i) {
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_done.wait(lk, [&] { return done[i] != 0; });
      write_pos = i + 1;
    }
    cv_room.notify_all();
    PackResult& r = results[i];
    if (r.ok && r.payload.size() > kLenMask) {
      r.ok = false;
      r.err = "record too large for the 29-bit length field";
    }
    if (!r.ok) {  // skip bad records, keep packing (Python semantics)
      fprintf(stderr, "im2rec: skip %s: %s\n", jobs[i].path.c_str(),
              r.err.c_str());
      continue;
    }
    const auto& p = r.payload;
    uint32_t head[2] = {kMagic, static_cast<uint32_t>(p.size())};
    uint32_t pad = (4 - (p.size() & 3u)) & 3u;
    uint32_t zero = 0;
    if (fwrite(head, 4, 2, rec) != 2 ||
        fwrite(p.data(), 1, p.size(), rec) != p.size() ||
        (pad && fwrite(&zero, 1, pad, rec) != pad) ||
        fprintf(idx, "%llu\t%llu\n",
                static_cast<unsigned long long>(jobs[i].id),
                static_cast<unsigned long long>(off)) < 0) {
      io_err = "write failed (disk full?) at record " +
               std::to_string(i);
      // stop the pool: abort_flag makes workers skip remaining decodes,
      // and the write_pos store happens under the mutex the waiters'
      // predicate reads under (no data race)
      {
        std::lock_guard<std::mutex> lk(mu);
        abort_flag.store(true);
        write_pos = jobs.size();
      }
      cv_room.notify_all();
      break;
    }
    off += 8 + p.size() + pad;
    ++written;
    // free the written payload promptly (the memory bound is the point)
    std::vector<uint8_t>().swap(r.payload);
  }
  for (auto& w : workers) w.join();
  bool close_ok = (fclose(rec) == 0) & (fclose(idx) == 0);
  if (!io_err.empty()) return fail(io_err);
  if (!close_ok) return fail("close failed (disk full?)");
  return written;
}

}  // extern "C"

extern "C" {

void* tmx_pipe_create_v2(const char* rec_path, int batch, int C, int H,
                         int W, int resize, int rand_crop, int rand_mirror,
                         const float* mean, const float* stdv, int threads,
                         int prefetch, int shuffle, uint64_t seed,
                         int label_width, int out_u8, int out_nhwc,
                         char* err, int errlen) {
  if (out_nhwc && C != 3) {
    snprintf(err, errlen,
             "out_nhwc requires 3-channel data_shape (got C=%d)", C);
    return nullptr;
  }
  auto* p = new Pipe();
  p->out_u8 = out_u8;
  p->out_nhwc = out_nhwc;
  std::string e;
  if (!p->file.Open(rec_path, &e) || p->file.records.empty()) {
    if (e.empty()) e = "empty recordio file";
    snprintf(err, errlen, "%s", e.c_str());
    delete p;
    return nullptr;
  }
  p->batch = batch;
  p->C = C;
  p->H = H;
  p->W = W;
  p->resize = resize;
  p->rand_crop = rand_crop;
  p->rand_mirror = rand_mirror;
  for (int i = 0; i < 3; ++i) {
    p->mean[i] = mean[i];
    p->stdv[i] = stdv[i] == 0.0f ? 1.0f : stdv[i];
  }
  p->nthreads = threads < 1 ? 1 : threads;
  p->prefetch = prefetch < 2 ? 2 : prefetch;
  p->shuffle = shuffle;
  p->seed = seed;
  p->label_width = label_width < 1 ? 1 : label_width;
  p->order.resize(p->file.records.size());
  for (size_t i = 0; i < p->order.size(); ++i) p->order[i] = i;
  p->AllocBufs();
  p->StartEpoch();
  return static_cast<PipeBase*>(p);
}

void* tmx_det_pipe_create_v2(const char* rec_path, int batch, int C, int H,
                             int W, int max_objects, int rand_crop,
                             int rand_mirror, const float* mean,
                             const float* stdv, float min_cover,
                             float area_lo, float area_hi, float ratio_lo,
                             float ratio_hi, int max_attempts, int threads,
                             int prefetch, int shuffle, uint64_t seed,
                             int out_u8, int out_nhwc, char* err,
                             int errlen) {
  if (out_nhwc && C != 3) {
    snprintf(err, errlen,
             "out_nhwc requires 3-channel data_shape (got C=%d)", C);
    return nullptr;
  }
  auto* p = new DetPipe();
  p->out_u8 = out_u8;
  p->out_nhwc = out_nhwc;
  std::string e;
  if (!p->file.Open(rec_path, &e) || p->file.records.empty()) {
    if (e.empty()) e = "empty recordio file";
    snprintf(err, errlen, "%s", e.c_str());
    delete p;
    return nullptr;
  }
  p->batch = batch;
  p->C = C;
  p->H = H;
  p->W = W;
  p->max_objects = max_objects < 1 ? 1 : max_objects;
  p->rand_crop = rand_crop;
  p->rand_mirror = rand_mirror;
  for (int i = 0; i < 3; ++i) {
    p->mean[i] = mean[i];
    p->stdv[i] = stdv[i] == 0.0f ? 1.0f : stdv[i];
  }
  p->min_cover = min_cover;
  p->area_lo = area_lo;
  p->area_hi = area_hi;
  p->ratio_lo = ratio_lo;
  p->ratio_hi = ratio_hi;
  p->max_attempts = max_attempts < 1 ? 1 : max_attempts;
  p->nthreads = threads < 1 ? 1 : threads;
  p->prefetch = prefetch < 2 ? 2 : prefetch;
  p->shuffle = shuffle;
  p->seed = seed;
  p->order.resize(p->file.records.size());
  for (size_t i = 0; i < p->order.size(); ++i) p->order[i] = i;
  p->AllocBufs();
  p->StartEpoch();
  return static_cast<PipeBase*>(p);
}

// the remaining entry points operate on the shared machinery and serve
// both pipe kinds: the classification binding passes a Pipe*, the
// detection binding a DetPipe* (both created above as their real type)
long long tmx_pipe_size(void* h) {
  return static_cast<PipeBase*>(h)->file.records.size();
}

int tmx_pipe_next(void* h, void* data, float* label) {
  return static_cast<PipeBase*>(h)->Next(data, label);
}

void tmx_pipe_reset(void* h) {
  PipeBase* p = static_cast<PipeBase*>(h);
  p->epoch++;
  p->StartEpoch();
}

const char* tmx_pipe_error(void* h) {
  return static_cast<PipeBase*>(h)->error.c_str();
}

void tmx_pipe_destroy(void* h) {
  PipeBase* p = static_cast<PipeBase*>(h);
  p->StopWorkers();
  delete p;
}

}  // extern "C"
